"""CLI REPL client + HTTP gateway tests (reference tiers:
hstream/app/client.hs REPL; hstream-http-server resource modules)."""

import io
import json
import time
import urllib.request

import grpc
import pytest

from hstream_tpu.client import Client, format_table
from hstream_tpu.http_gateway import serve_gateway
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached

BASE = 1_700_000_000_000


@pytest.fixture(scope="module")
def stack():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    addr = f"127.0.0.1:{ctx.port}"
    httpd, gw = serve_gateway(addr, port=0)
    http_base = f"http://127.0.0.1:{httpd.server_port}"
    channel = grpc.insecure_channel(addr)
    stub = HStreamApiStub(channel)
    yield addr, http_base, stub, ctx
    channel.close()
    httpd.shutdown()
    gw.close()
    server.stop(grace=1)
    ctx.shutdown()


def _http_full(method, base, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(base + path, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(
                resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _http(method, base, path, body=None):
    code, payload, _ = _http_full(method, base, path, body)
    return code, payload


# ---- REPL -------------------------------------------------------------------


def test_repl_scripted_session(stack):
    addr, _, _, _ = stack
    out = io.StringIO()
    client = Client(addr, out=out)
    try:
        client.repl(input_lines=[
            "CREATE STREAM shell_s;",
            "INSERT INTO shell_s (city, temp)",   # multi-line statement
            "  VALUES ('sf', 21.5);",
            "SHOW STREAMS;",
            "EXPLAIN SELECT COUNT(*) FROM shell_s GROUP BY city "
            "EMIT CHANGES;",
            "SELECT nope FROM;",                  # parse error, non-fatal
            "\\q",
        ])
    finally:
        client.close()
    text = out.getvalue()
    assert "shell_s" in text               # SHOW STREAMS table
    assert "lsn" in text                   # INSERT result row
    assert "AGGREGATE" in text             # EXPLAIN output
    assert "parse error" in text           # bad SQL reported, shell alive


def test_repl_ddl_routing_and_pull_query(stack):
    addr, _, stub, ctx = stack
    out = io.StringIO()
    client = Client(addr, out=out)
    try:
        client.execute("CREATE STREAM replsrc;")
        client.execute(
            "CREATE VIEW replview AS SELECT city, COUNT(*) AS c "
            "FROM replsrc GROUP BY city, TUMBLING (INTERVAL 10 SECOND) "
            "GRACE BY INTERVAL 0 SECOND;")
        wait_attached(ctx, "view-replview")
        from hstream_tpu.common import records as rec

        req = pb.AppendRequest(stream_name="replsrc")
        for i, city in enumerate(["sf", "sf", "la"]):
            req.records.append(rec.build_record(
                {"city": city}, publish_time_ms=BASE + i))
        req.records.append(rec.build_record({"city": "zz"},
                                            publish_time_ms=BASE + 30_000))
        stub.Append(req)
        deadline = time.time() + 30
        while time.time() < deadline:
            out.truncate(0)
            out.seek(0)
            client.execute("SELECT * FROM replview WHERE city = 'sf';")
            if "| 2" in out.getvalue() or " 2 " in out.getvalue():
                break
            time.sleep(0.2)
        assert "sf" in out.getvalue(), out.getvalue()
    finally:
        client.close()


def test_format_table_alignment():
    t = format_table([{"a": 1, "b": "xy"}, {"a": 200, "b": None}])
    lines = t.splitlines()
    assert lines[1].startswith("| a") and "b" in lines[1]
    assert "NULL" in t and "(2 rows)" in t
    assert format_table([]) == "(0 rows)"


# ---- HTTP gateway -----------------------------------------------------------


def test_http_stream_crud_and_append(stack):
    _, base, _, _ = stack
    code, _ = _http("POST", base, "/streams", {"name": "hs1"})
    assert code == 201
    code, streams = _http("GET", base, "/streams")
    assert code == 200 and any(s["name"] == "hs1" for s in streams)
    code, out = _http("POST", base, "/streams/hs1/append",
                      {"records": [{"a": 1, "__time_ms": BASE},
                                   {"a": 2, "__time_ms": BASE + 1}]})
    assert code == 200 and len(out["record_ids"]) == 2
    code, _ = _http("DELETE", base, "/streams/hs1")
    assert code == 200
    code, err = _http("DELETE", base, "/streams/hs1")
    assert code == 404 and "error" in err


def test_http_query_lifecycle(stack):
    _, base, _, _ = stack
    _http("POST", base, "/streams", {"name": "hqsrc"})
    code, q = _http("POST", base, "/queries",
                    {"sql": "SELECT a, COUNT(*) AS c FROM hqsrc "
                            "GROUP BY a, TUMBLING (INTERVAL 10 SECOND) "
                            "EMIT CHANGES;"})
    assert code == 201 and q["id"]
    qid = q["id"]
    code, got = _http("GET", base, f"/queries/{qid}")
    assert code == 200 and got["sql"].startswith("SELECT")
    code, qs = _http("GET", base, "/queries")
    assert any(x["id"] == qid for x in qs)
    code, _ = _http("DELETE", base, f"/queries/{qid}")
    assert code == 200
    code, _ = _http("GET", base, f"/queries/{qid}")
    assert code == 404


def test_http_views_and_overview_stats(stack):
    _, base, stub, ctx = stack
    _http("POST", base, "/streams", {"name": "hvsrc"})
    from hstream_tpu.common import records as rec

    code, _ = _http("POST", base, "/queries",
                    {"sql": "CREATE VIEW hview AS SELECT k, "
                            "COUNT(*) AS c FROM hvsrc GROUP BY k, "
                            "TUMBLING (INTERVAL 10 SECOND) "
                            "GRACE BY INTERVAL 0 SECOND;"})
    # CreateQuery rejects non-EMIT-CHANGES -> create via the gRPC path
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW hview AS SELECT k, COUNT(*) AS c "
                  "FROM hvsrc GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-hview")
    _http("POST", base, "/streams/hvsrc/append",
          {"records": [{"k": "a", "__time_ms": BASE},
                       {"k": "a", "__time_ms": BASE + 1},
                       {"k": "b", "__time_ms": BASE + 2}]})
    _http("POST", base, "/streams/hvsrc/append",
          {"records": [{"k": "zz", "__time_ms": BASE + 30_000}]})
    code, views = _http("GET", base, "/views")
    assert code == 200 and any(v["name"] == "hview" for v in views)
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        code, rows = _http("GET", base, "/views/hview")
        if any(r.get("k") == "a" and r.get("c") == 2 for r in rows):
            break
        time.sleep(0.2)
    assert any(r.get("k") == "a" and r.get("c") == 2 for r in rows), rows

    code, ov = _http("GET", base, "/overview")
    assert code == 200
    assert ov["streams"] >= 1 and ov["nodes"][0]["status"] == "Running"
    by_stream = {s["stream"]: s for s in ov["stats"]}
    assert by_stream["hvsrc"]["counters"]["append_total"] >= 2
    assert "append_in_bytes" in by_stream["hvsrc"]["rates"]

    code, _ = _http("DELETE", base, "/views/hview")
    assert code == 200


def test_http_connectors_and_nodes(stack):
    _, base, _, _ = stack
    code, nodes = _http("GET", base, "/nodes")
    assert code == 200 and nodes[0]["status"] == "Running"
    code, sw = _http("GET", base, "/swagger.json")
    assert code == 200 and "/overview" in sw["paths"]
    code, conns = _http("GET", base, "/connectors")
    assert code == 200 and conns == []
    code, err = _http("POST", base, "/connectors", {})
    assert code == 400


def test_http_malformed_bodies_get_json_errors(stack):
    """Bad field types / shapes must return JSON 4xx, not a dropped
    connection (pre-fix: TypeError escaped the handler)."""
    _, base, _, _ = stack
    code, err = _http("POST", base, "/streams",
                      {"name": "x1", "replication_factor": "two"})
    assert code == 400 and "error" in err
    _http("POST", base, "/streams", {"name": "x1"})
    code, err = _http("POST", base, "/streams/x1/append",
                      {"records": ["oops"]})
    assert code == 400 and "error" in err
    # query strings don't break routing
    code, _ = _http("GET", base, "/streams?foo=1")
    assert code == 200


def test_getstats_excludes_deleted_streams(stack):
    _, base, stub, _ = stack
    _http("POST", base, "/streams", {"name": "gone"})
    _http("POST", base, "/streams/gone/append",
          {"records": [{"a": 1}]})
    _http("DELETE", base, "/streams/gone")
    out = stub.GetStats(pb.GetStatsRequest())
    assert not any(s.stream_name == "gone" for s in out.stats)


def test_grpc_getstats_direct(stack):
    _, _, stub, _ = stack
    out = stub.GetStats(pb.GetStatsRequest())
    assert any(s.counters.get("append_total", 0) > 0 for s in out.stats)


# ---- flow control at the boundaries ----------------------------------------


def _admin(stub, command, **kwargs):
    from hstream_tpu.common import records as rec

    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command=command, args=rec.dict_to_struct(kwargs)))
    return json.loads(resp.result)


def test_http_error_status_mapping(stack):
    """ServerError codes map to proper HTTP statuses: 404 not-found,
    409 already-exists, 429 resource-exhausted with Retry-After."""
    _, base, stub, _ = stack
    code, err = _http("GET", base, "/queries/does-not-exist")
    assert code == 404 and "error" in err
    _http("POST", base, "/streams", {"name": "dupes"})
    code, err = _http("POST", base, "/streams", {"name": "dupes"})
    assert code == 409 and "error" in err
    # one-record burst with a near-zero refill rate: the first append
    # drains it and no CI-runner pause can refill before the second,
    # which must come back as HTTP 429 carrying the retry-after contract
    _admin(stub, "quota-set", scope="stream/dupes",
           records_per_s=0.001, burst_records=1)
    try:
        code, _ = _http("POST", base, "/streams/dupes/append",
                        {"records": [{"a": 1}]})
        assert code == 200
        code, err, headers = _http_full(
            "POST", base, "/streams/dupes/append",
            {"records": [{"a": 2}]})
        assert code == 429, err
        assert int(headers["Retry-After"]) >= 1
        assert err["retry_after_ms"] >= 1
        assert "retry_after_ms=" in err["error"]
    finally:
        _admin(stub, "quota-unset", scope="stream/dupes")


def test_client_retry_helper_rides_out_quota(stack):
    """The REPL client's retry policy converges on a throttled stream
    and surfaces the retry count."""
    from hstream_tpu.common import records as rec

    addr, _, stub, _ = stack
    out = io.StringIO()
    client = Client(addr, out=out)
    client.execute("CREATE STREAM rlim;")
    # slow refill (2/s): after draining the burst below, the client's
    # INSERT is guaranteed a refusal — a ~500ms token gap cannot be
    # covered by call latency — and the retry hint covers the wait
    _admin(stub, "quota-set", scope="stream/rlim",
           records_per_s=2, burst_records=4)
    try:
        req = pb.AppendRequest(stream_name="rlim")
        for i in range(4):  # drain the whole burst in one append
            req.records.append(rec.build_record({"a": i}))
        stub.Append(req)
        client.execute("INSERT INTO rlim (a) VALUES (99);")
        text = out.getvalue()
        assert "server error" not in text, text
        assert "lsn" in text              # the insert landed...
        assert client.retries > 0         # ...after backoff
    finally:
        _admin(stub, "quota-unset", scope="stream/rlim")
        client.close()


def test_flow_status_and_quota_admin_verbs(stack):
    _, _, stub, _ = stack
    _admin(stub, "quota-set", scope="tenant/acme", records_per_s=9)
    try:
        got = _admin(stub, "quota-get", scope="tenant/acme")
        assert got["records_per_s"] == 9
        assert "tenant/acme" in _admin(stub, "quota-list")
        status = _admin(stub, "flow-status")
        assert status["level"] in ("admit", "defer", "reject")
        assert status["active"] is True
        assert "pipeline_occupancy" in status["signals"]
        assert status["quotas"]["tenant/acme"]["records_per_s"] == 9
    finally:
        _admin(stub, "quota-unset", scope="tenant/acme")
    got = _admin(stub, "quota-get", scope="tenant/acme")
    assert got.get("unset") is True


# ---- ISSUE 9: failover-aware client + gateway (NOT_LEADER hint) -------------


class _FencedServicer:
    """Every RPC answers like a fenced store leader: UNAVAILABLE with
    the new leader's address in trailing metadata AND the message."""

    def __init__(self, hint: str):
        self.hint = hint
        self.hits = 0

    def __getattr__(self, name):
        def handler(request, context):
            self.hits += 1
            context.set_trailing_metadata(
                (("x-leader-hint", self.hint),))
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"store leadership lost: fenced by epoch 2 "
                f"(not_leader leader_hint={self.hint})")

        return handler


@pytest.fixture()
def fenced_pair(stack):
    """A fenced fake leader whose hint points at the REAL server."""
    from concurrent import futures

    from hstream_tpu.proto.rpc import add_hstream_api_to_server

    addr, _http, _stub, _ctx = stack
    fake = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    svc = _FencedServicer(addr)
    add_hstream_api_to_server(svc, fake)
    fport = fake.add_insecure_port("127.0.0.1:0")
    fake.start()
    yield f"127.0.0.1:{fport}", svc
    fake.stop(grace=1)


def test_retry_policy_follows_hint_only_with_callback_and_hint():
    """Unit contract: UNAVAILABLE + hint retries through the callback;
    bare UNAVAILABLE (no hint — a mid-call drop) raises immediately
    even WITH a callback; hinted errors raise without a callback."""
    from hstream_tpu.client.retry import (
        HINTED_RETRYABLE_CODES,
        RetryPolicy,
        leader_hint_from_error,
    )

    class _Err(grpc.RpcError):
        def __init__(self, details="", md=()):
            self._d, self._md = details, md

        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return self._d

        def trailing_metadata(self):
            return self._md

    hinted = _Err(md=(("x-leader-hint", "new:1"),))
    texted = _Err("x (not_leader leader_hint=new:2)")
    bare = _Err("connection reset")
    assert leader_hint_from_error(hinted) == "new:1"
    assert leader_hint_from_error(texted) == "new:2"  # text fallback
    assert leader_hint_from_error(bare) is None
    assert grpc.StatusCode.UNAVAILABLE in HINTED_RETRYABLE_CODES

    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] == 1:
            raise hinted
        return "ok"

    followed = []
    policy = RetryPolicy(attempts=3, sleep=lambda s: None)
    assert policy.call(fn, on_leader_hint=followed.append) == "ok"
    assert followed == ["new:1"]
    assert policy.leader_follows == 1

    calls["n"] = 0
    with pytest.raises(grpc.RpcError):  # no callback: not followable
        RetryPolicy(attempts=3, sleep=lambda s: None).call(fn)

    def always_bare():
        raise bare

    with pytest.raises(grpc.RpcError):  # hintless: never retried
        policy.call(always_bare, on_leader_hint=followed.append)
    assert followed == ["new:1"]  # callback not invoked again


def test_client_follows_leader_hint_across_statements(fenced_pair,
                                                      stack):
    """The SQL client pointed at a fenced leader follows the hint mid-
    statement: the CREATE lands on the new leader and the session stays
    rebound for everything after."""
    fenced_addr, svc = fenced_pair
    addr, _http, stub, _ctx = stack
    out = io.StringIO()
    client = Client(fenced_addr, out=out)
    try:
        client.execute("CREATE STREAM failover_cli;")
        assert client.addr == addr  # rebound to the hinted leader
        assert client.retry.leader_follows == 1
        assert svc.hits == 1
        streams = {s.stream_name for s in stub.ListStreams(
            pb.ListStreamsRequest()).streams}
        assert "failover_cli" in streams
        assert "following hint" in out.getvalue()
        # the NEXT statement goes straight to the new leader
        client.execute("CREATE STREAM failover_cli2;")
        assert svc.hits == 1
        assert client.retry.leader_follows == 1
    finally:
        client.close()


def test_gateway_follows_leader_hint_and_rebinds(fenced_pair, stack):
    """An HTTP caller behind the gateway never sees the failover: the
    gateway follows the NOT_LEADER hint, retries the request against
    the new leader, and keeps the rebound channel for later requests."""
    from hstream_tpu.http_gateway import serve_gateway

    fenced_addr, svc = fenced_pair
    addr, _http_base, stub, _ctx = stack
    httpd, gw = serve_gateway(fenced_addr, port=0)
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        code, payload = _http("POST", base, "/streams",
                              {"name": "failover_gw"})
        assert code == 201, payload
        assert gw.leader_follows == 1
        assert gw.server_addr == addr
        assert svc.hits == 1
        streams = {s.stream_name for s in stub.ListStreams(
            pb.ListStreamsRequest()).streams}
        assert "failover_gw" in streams
        # next request rides the rebound channel directly
        code, payload = _http("GET", base, "/streams")
        assert code == 200
        assert svc.hits == 1
    finally:
        httpd.shutdown()
        gw.close()


def test_gateway_surfaces_hint_when_retry_also_fails(fenced_pair):
    """If the hinted leader is ALSO unreachable/fenced, the gateway
    still answers 503 with the hint in the body so the HTTP caller can
    act on it."""
    from hstream_tpu.http_gateway import Gateway

    fenced_addr, svc = fenced_pair
    # a gateway whose fenced leader hints at... the same fenced leader
    svc.hint = fenced_addr
    gw = Gateway(fenced_addr)
    try:
        out = gw.handle("GET", "/streams", None)
        assert out[0] == 503
        assert out[1]["leader_hint"] == fenced_addr
        assert len(out) == 2 or "x-follow-leader" not in (out[2] or {})
    finally:
        gw.close()
