"""Reusable state-store surface (reference Store.hs classes)."""

from hstream_tpu.engine.statestore import (
    LastValueStore,
    TimestampedKVStore,
)


def test_timestamped_kvstore_range_prune():
    ts_store = TimestampedKVStore()
    for t in (30, 10, 20):
        ts_store.put(("a",), t, {"t": t})
    ts_store.put(("b",), 15, {"t": 15})
    assert [t for t, _ in ts_store.range(("a",), 10, 20)] == [10, 20]
    assert ts_store.range(("zz",), 0, 99) == []
    ts_store.prune(15)
    assert [t for t, _ in ts_store.range(("a",), 0, 99)] == [20, 30]
    assert ts_store.range(("b",), 0, 99) == [(15, {"t": 15})]
    ts_store.prune(99)
    assert ts_store.by_key == {}


def test_last_value_store_out_of_order():
    lv = LastValueStore()
    lv.update(("k",), 10, {"v": "old"})
    lv.update(("k",), 30, {"v": "new"})
    lv.update(("k",), 20, {"v": "stale"})  # must not clobber newer
    assert lv.lookup(("k",)) == {"v": "new"}
    assert lv.lookup(("other",)) is None
    assert len(lv) == 1
