import numpy as np
import jax.numpy as jnp

from hstream_tpu.engine.sketches import (
    HLLConfig,
    QuantileConfig,
    clz32,
    hash_u32,
    hll_estimate,
    hll_update_indices,
    quantile_bin,
    quantile_estimate,
)


def test_clz32():
    xs = jnp.array([0, 1, 2, 3, 0x80000000, 0xFFFFFFFF, 0x00010000],
                   dtype=jnp.uint32)
    expect = [32, 31, 30, 30, 0, 0, 15]
    assert clz32(xs).tolist() == expect


def test_hash_spread():
    vals = jnp.arange(10_000, dtype=jnp.int32)
    hs = np.asarray(hash_u32(vals))
    assert len(np.unique(hs)) > 9_990  # essentially no collisions
    # top byte should be roughly uniform
    top = hs >> 24
    counts = np.bincount(top, minlength=256)
    assert counts.min() > 0


def test_hll_accuracy():
    cfg = HLLConfig(precision=10)
    for true_n in (100, 5_000, 50_000):
        vals = jnp.arange(true_n, dtype=jnp.float32)
        reg, rank = hll_update_indices(vals, cfg)
        registers = jnp.zeros((cfg.m,), jnp.int8).at[reg].max(rank)
        est = float(hll_estimate(registers, cfg))
        assert abs(est - true_n) / true_n < 0.15, (true_n, est)


def test_hll_merge_equals_union():
    cfg = HLLConfig(precision=10)
    a_vals = jnp.arange(0, 3000, dtype=jnp.float32)
    b_vals = jnp.arange(1500, 4500, dtype=jnp.float32)
    def regs(vals):
        reg, rank = hll_update_indices(vals, cfg)
        return jnp.zeros((cfg.m,), jnp.int8).at[reg].max(rank)
    merged = jnp.maximum(regs(a_vals), regs(b_vals))
    union = regs(jnp.arange(0, 4500, dtype=jnp.float32))
    assert float(hll_estimate(merged, cfg)) == float(hll_estimate(union, cfg))


def test_quantile_accuracy():
    cfg = QuantileConfig()
    rng = np.random.default_rng(0)
    vals = rng.lognormal(mean=3.0, sigma=1.5, size=20_000).astype(np.float32)
    bins = quantile_bin(jnp.asarray(vals), cfg)
    hist = jnp.zeros((cfg.n_bins,), jnp.int32).at[bins].add(1)
    for q in (0.5, 0.9, 0.99):
        est = float(quantile_estimate(hist, q, cfg))
        true = float(np.quantile(vals, q))
        assert abs(est - true) / true < 0.10, (q, true, est)


def test_quantile_zero_and_small():
    cfg = QuantileConfig()
    vals = jnp.asarray([0.0, 0.0, 1e-9], dtype=jnp.float32)
    bins = quantile_bin(vals, cfg)
    assert bins.tolist() == [0, 0, 0]
    hist = jnp.zeros((cfg.n_bins,), jnp.int32).at[bins].add(1)
    assert float(quantile_estimate(hist, 0.5, cfg)) == 0.0
