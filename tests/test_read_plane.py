"""Read plane (ISSUE 20): snapshot cache exactness, bounded staleness,
closed-only fast path, columnwise/row serve parity, shared-encode
subscription fan-out, and a concurrent-reader exactness stress under
the armed lock-order witness.

The cache's contract is EXACT equality: a cached serve must be
byte-identical (canonical JSON) to the uncached pipeline at the same
version — across window closes, late data, and concurrent mutation.
"""

import json
import threading
import time

import grpc
import numpy as np
import pytest

from hstream_tpu.common import locktrace, records as rec
from hstream_tpu.common.columnar import ColumnarEmit
from hstream_tpu.common.locktrace import LOCKTRACE
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server import views as views_mod
from hstream_tpu.server.context import ServerContext
from hstream_tpu.server.main import serve
from hstream_tpu.server.readcache import ReadCache
from hstream_tpu.server.views import (
    Materialization,
    filter_rows,
    project_rows,
    serve_select_view,
)
from hstream_tpu.sql.codegen import stream_codegen
from hstream_tpu.store import open_store

from helpers import wait_attached

BASE = 1_700_000_000_000


def _pull(sql: str):
    """The SELECT of a pull-query statement (SelectViewPlan.select)."""
    return stream_codegen(sql).select


def _canon(rows) -> str:
    """Canonical byte form for exactness comparisons (numpy scalars
    normalize through `float`, dict order through sort_keys)."""
    return json.dumps(list(rows), sort_keys=True, default=float)


class _FakeEx:
    """Executor stand-in with the read-plane surface: a monotone
    read_version, a peek counter, and a controllable live floor."""

    def __init__(self, live_rows=None, live_lo=None):
        self.live_rows = list(live_rows or [])
        self.live_lo = live_lo
        self.peeks = 0
        self.ver = 0

    def peek(self):
        self.peeks += 1
        return list(self.live_rows)

    def read_version(self):
        return ("fake", id(self), self.ver)

    def live_min_win_end(self):
        return self.live_lo


class _FakeTask:
    def __init__(self, ex):
        self.state_lock = locktrace.rlock("tasks.state")
        self.executor = ex


def _view(ex, closed_rows=()):
    mat = Materialization(group_cols=["k"])
    mat.task = _FakeTask(ex)
    if closed_rows:
        mat.add_closed(list(closed_rows))
    return mat


# ---- snapshot cache: exactness + version invalidation -----------------------


def test_cache_hit_is_byte_identical_and_close_invalidates():
    ex = _FakeEx(live_rows=[{"k": "a", "c": 2, "winStart": BASE,
                             "winEnd": BASE + 10_000}])
    mat = _view(ex, [{"k": "a", "c": 5, "winStart": BASE - 10_000,
                      "winEnd": BASE}])
    sel = _pull("SELECT * FROM v;")
    cache = ReadCache()

    r1, how1, x1 = cache.serve_view("v", mat, sel, "q1")
    assert (how1, x1, ex.peeks) == ("miss", True, 1)
    r2, how2, x2 = cache.serve_view("v", mat, sel, "q1")
    assert (how2, x2, ex.peeks) == ("hit", False, 1)  # no second peek
    assert _canon(r1) == _canon(r2)
    # byte-identical to the uncached pipeline at the same version
    assert _canon(r2) == _canon(serve_select_view(mat, sel))

    # a window close mutates BOTH halves: closed store + executor epoch
    mat.add_closed([{"k": "a", "c": 7, "winStart": BASE,
                     "winEnd": BASE + 10_000}])
    ex.live_rows = []
    ex.ver += 1
    r3, how3, _ = cache.serve_view("v", mat, sel, "q1")
    assert how3 == "miss"  # version advanced -> stale entry invalid
    assert _canon(r3) == _canon(serve_select_view(mat, sel))
    assert any(r["c"] == 7 for r in r3)

    # late data changing only the executor half also invalidates
    ex.live_rows = [{"k": "a", "c": 1, "winStart": BASE + 10_000,
                     "winEnd": BASE + 20_000}]
    ex.ver += 1
    r4, how4, _ = cache.serve_view("v", mat, sel, "q1")
    assert how4 == "miss"
    assert _canon(r4) == _canon(serve_select_view(mat, sel))
    assert cache.hit_ratio() == pytest.approx(1 / 4)


def test_distinct_statements_cache_separately():
    ex = _FakeEx()
    mat = _view(ex, [{"k": "a", "c": 5, "winStart": BASE,
                      "winEnd": BASE + 10_000},
                     {"k": "b", "c": 9, "winStart": BASE,
                      "winEnd": BASE + 10_000}])
    cache = ReadCache()
    all_sel = _pull("SELECT * FROM v;")
    one_sel = _pull("SELECT * FROM v WHERE k = 'a';")
    rows_all, _, _ = cache.serve_view("v", mat, all_sel,
                                      "SELECT * FROM v;")
    rows_one, how, _ = cache.serve_view("v", mat, one_sel,
                                        "SELECT * FROM v WHERE k = 'a';")
    assert how == "miss"  # different statement, different entry
    assert len(rows_all) == 2 and len(rows_one) == 1
    assert _canon(rows_one) == _canon(serve_select_view(mat, one_sel))


def test_unversioned_executor_bypasses_cache():
    class _Bare:  # no read_version: exactness unprovable -> never cache
        def peek(self):
            return []

    mat = _view(_Bare(), [{"k": "a", "c": 1, "winStart": BASE,
                           "winEnd": BASE + 10_000}])
    cache = ReadCache()
    sel = _pull("SELECT * FROM v;")
    _, how1, x1 = cache.serve_view("v", mat, sel, "q")
    _, how2, x2 = cache.serve_view("v", mat, sel, "q")
    assert (how1, how2) == ("bypass", "bypass")
    assert x1 and x2 and cache.stats()["bypasses"] == 2


# ---- bounded staleness ------------------------------------------------------


def test_staleness_bound_expires_hits():
    now = [100.0]
    ex = _FakeEx()
    mat = _view(ex, [{"k": "a", "c": 1, "winStart": BASE,
                      "winEnd": BASE + 10_000}])
    sel = _pull("SELECT * FROM v;")
    cache = ReadCache(max_staleness_ms=250.0, clock=lambda: now[0])
    _, how1, _ = cache.serve_view("v", mat, sel, "q")
    now[0] += 0.2  # +200ms: inside the bound
    _, how2, _ = cache.serve_view("v", mat, sel, "q")
    now[0] += 0.2  # +400ms total: past the bound, version unchanged
    r3, how3, _ = cache.serve_view("v", mat, sel, "q")
    assert (how1, how2, how3) == ("miss", "hit", "miss")
    assert _canon(r3) == _canon(serve_select_view(mat, sel))
    # recompute restamps the entry: fresh again
    _, how4, _ = cache.serve_view("v", mat, sel, "q")
    assert how4 == "hit"


# ---- closed-only fast path (satellite: no executor touch) -------------------


def test_closed_only_where_skips_live_peek():
    closed = [{"k": "a", "c": 5, "winStart": BASE - 10_000,
               "winEnd": BASE}]
    ex = _FakeEx(live_rows=[{"k": "a", "c": 1, "winStart": BASE,
                             "winEnd": BASE + 10_000}],
                 live_lo=BASE + 10_000)
    mat = _view(ex, closed)
    # strictly below every live winEnd: the peek is provably empty
    sel = _pull(f"SELECT * FROM v WHERE winEnd <= {BASE};")
    rows = serve_select_view(mat, sel)
    assert ex.peeks == 0
    assert _canon(rows) == _canon(
        project_rows(filter_rows(closed, sel), sel,
                     keep_meta=("winStart", "winEnd")))
    # non-strict bound EQUAL to the live floor can match a live row:
    # the peek must run
    sel2 = _pull(f"SELECT * FROM v WHERE winEnd <= {BASE + 10_000};")
    rows2 = serve_select_view(mat, sel2)
    assert ex.peeks == 1
    assert any(r["winStart"] == BASE for r in rows2)
    # unbounded WHERE always peeks
    serve_select_view(mat, _pull("SELECT * FROM v WHERE c > 0;"))
    assert ex.peeks == 2


def test_closed_only_skips_real_executor_peek():
    """Against a REAL device-backed executor: a closed-bounded pull
    never extracts the arena (live_min_win_end is host arithmetic)."""
    from hstream_tpu.engine import (
        AggKind, AggSpec, AggregateNode, ColumnType, QueryExecutor,
        Schema, SourceNode, TumblingWindow,
    )
    from hstream_tpu.engine.expr import Col

    schema = Schema.of(k=ColumnType.STRING, v=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode(stream="s", schema=schema),
        group_keys=[Col("k")], window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c")], having=None,
        post_projections=[])
    ex = QueryExecutor(node, schema, emit_changes=False, initial_keys=8,
                       batch_capacity=64)
    ex.process([{"k": "a"}, {"k": "b"}], [BASE, BASE + 1000])
    assert ex.live_min_win_end() == BASE + 10_000
    mat = _view(ex, [{"k": "z", "c": 1, "winStart": BASE - 10_000,
                      "winEnd": BASE}])
    mat.task.executor = ex
    peeks = []
    orig = ex.peek
    ex.peek = lambda: (peeks.append(1), orig())[1]
    closed_sel = _pull(f"SELECT * FROM v WHERE winEnd < {BASE + 1};")
    rows = serve_select_view(mat, closed_sel)
    assert peeks == [] and [r["k"] for r in rows] == ["z"]
    live_sel = _pull("SELECT * FROM v;")
    rows_all = serve_select_view(mat, live_sel)
    assert len(peeks) == 1 and {r["k"] for r in rows_all} == {"a", "b",
                                                             "z"}


# ---- columnwise serve parity ------------------------------------------------


def test_where_projection_columnwise_matches_row_path():
    emit = ColumnarEmit(
        {"k": np.array(["a", "b", "c", "d"], object),
         "c": np.array([1, 2, 3, 4], np.int64),
         "t": np.array([1.5, 2.5, 3.5, 4.5]),
         "winStart": np.full(4, BASE, np.int64),
         "winEnd": np.full(4, BASE + 10_000, np.int64)}, 4)
    for sql in ("SELECT * FROM v WHERE c > 1;",
                "SELECT k, c FROM v WHERE c >= 2 AND t < 4.0;",
                "SELECT k AS g, t FROM v;",
                "SELECT * FROM v WHERE k = 'b';",
                "SELECT k FROM v WHERE c > 100;"):
        sel = _pull(sql)
        got = views_mod._select_emit(emit, sel)
        want = project_rows(filter_rows(list(emit), sel), sel,
                            keep_meta=("winStart", "winEnd"))
        assert _canon(got) == _canon(want), sql


def test_columnwise_failure_falls_back_to_exact_rows(monkeypatch):
    emit = ColumnarEmit({"k": np.array(["a", "b"], object),
                         "c": np.array([1, 2], np.int64)}, 2)
    sel = _pull("SELECT * FROM v WHERE c > 1;")
    want = views_mod._select_emit(emit, sel)

    def boom(*a, **kw):
        raise RuntimeError("vector path down")

    monkeypatch.setattr(views_mod, "_select_emit_cols", boom)
    assert _canon(views_mod._select_emit(emit, sel)) == _canon(want)


# ---- budget / eviction / invalidation ---------------------------------------


def test_byte_budget_evicts_and_bounds():
    ex = _FakeEx()
    mat = _view(ex, [{"k": f"k{i}", "c": i, "winStart": BASE,
                      "winEnd": BASE + 10_000} for i in range(50)])
    cache = ReadCache(max_bytes=4096)
    for i in range(30):
        sql = f"SELECT * FROM v WHERE c = {i};"
        cache.serve_view("v", mat, _pull(sql), sql)
    assert cache.nbytes() <= 4096
    assert cache.stats()["evictions"] > 0


def test_drop_view_frees_budget():
    ex = _FakeEx()
    mat = _view(ex, [{"k": "a", "c": 1, "winStart": BASE,
                      "winEnd": BASE + 10_000}])
    cache = ReadCache()
    cache.serve_view("v", mat, _pull("SELECT * FROM v;"), "q")
    assert cache.nbytes() > 0
    cache.invalidate_view("v")
    assert cache.nbytes() == 0
    assert cache.stats()["invalidations"] == 1


# ---- shared-encode subscription fan-out -------------------------------------


def test_fanout_shares_expanded_frames_across_consumers():
    """One columnar sink record, N subscriptions: every consumer gets
    byte-identical frames that are the SAME objects (encode-once), and
    the expansion ran exactly once per payload."""
    from hstream_tpu.common import columnar

    N = 4
    ctx = ServerContext(open_store("mem://"))
    try:
        ctx.streams.create_stream("fanout")
        logid = ctx.streams.get_logid("fanout")
        rows = [{"k": f"g{i}", "c": i, "winStart": BASE + i}
                for i in range(16)]
        packed = columnar.rows_to_payload(rows, BASE)
        assert packed is not None
        ctx.store.append(logid, rec.build_record(packed)
                         .SerializeToString())
        fetched = []
        for i in range(N):
            rt = ctx.subscriptions.create(
                ctx, pb.Subscription(subscription_id=f"fo{i}",
                                     stream_name="fanout"))
            fetched.append(rt.fetch(timeout_ms=200, max_size=256))
        assert all(len(got) == len(rows) for got in fetched)
        first = fetched[0]
        for got in fetched[1:]:
            for (rid_a, pay_a), (rid_b, pay_b) in zip(first, got):
                assert rid_a == rid_b and pay_a == pay_b
                assert pay_a is pay_b  # shared BY REFERENCE
        st = ctx.read_cache.stats()
        assert st["expand_misses"] == 1
        assert st["expand_hits"] == N - 1
        # the delivered frames decode back to the emitted rows
        decoded = [rec.record_to_dict(rec.parse_record(p))
                   for _rid, p in first]
        assert decoded == rows
        # read_out_records carries the subscription drains
        ladder = ctx.stats.stat_ladder("read_out_records", "fanout")
        assert ladder["total"] == float(len(rows) * N)
    finally:
        ctx.shutdown()


def test_fanout_without_cache_still_serves():
    from hstream_tpu.common import columnar

    ctx = ServerContext(open_store("mem://"), read_cache_bytes=0)
    try:
        assert ctx.read_cache is None
        ctx.streams.create_stream("nocache")
        logid = ctx.streams.get_logid("nocache")
        packed = columnar.rows_to_payload(
            [{"k": "a", "c": 1}, {"k": "b", "c": 2}], BASE)
        ctx.store.append(logid, rec.build_record(packed)
                         .SerializeToString())
        rt = ctx.subscriptions.create(
            ctx, pb.Subscription(subscription_id="nc",
                                 stream_name="nocache"))
        got = rt.fetch(timeout_ms=200, max_size=256)
        assert [rec.record_to_dict(rec.parse_record(p))["k"]
                for _r, p in got] == ["a", "b"]
    finally:
        ctx.shutdown()


# ---- end-to-end: pull queries through the server ----------------------------


@pytest.fixture()
def server_stub():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    yield stub, ctx
    channel.close()
    server.stop(grace=1)
    ctx.shutdown()


def _append(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    stub.Append(req)


def test_pull_query_cached_end_to_end(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="rpsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW rpview AS SELECT city, COUNT(*) AS c "
                  "FROM rpsrc GROUP BY city, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-rpview")
    _append(stub, "rpsrc", [{"city": "sf"}, {"city": "la"},
                            {"city": "la"}], [BASE, BASE + 1, BASE + 2])
    _append(stub, "rpsrc", [{"city": "zz"}], [BASE + 30_000])  # closer
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM rpview;"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        if any(r.get("winStart") == BASE and r.get("city") == "la"
               and r.get("c") == 2 for r in rows):
            break
        time.sleep(0.2)
    closed = {r["city"]: r["c"] for r in rows
              if r.get("winStart") == BASE}
    assert closed.get("sf") == 1 and closed.get("la") == 2, rows
    # quiesce: poll until two consecutive pulls agree byte-for-byte
    # (the engine may still be absorbing the closer record), then the
    # next pull must be a version-valid HIT with the identical answer
    def _pull_rows():
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM rpview;"))
        return [rec.struct_to_dict(s) for s in resp.result_set]

    deadline = time.time() + 10
    prev = _canon(rows)
    while time.time() < deadline:
        cur = _canon(_pull_rows())
        if cur == prev:
            break
        prev = cur
        time.sleep(0.1)
    hits0 = ctx.read_cache.stats()["hits"]
    assert _canon(_pull_rows()) == prev
    assert ctx.read_cache.stats()["hits"] > hits0
    # the stat family + counter carry the serves (view-labeled)
    assert ctx.stats.stat_ladder("read_out_records",
                                 "rpview")["total"] > 0
    assert ctx.stats.stream_stat_get("read_extracts", "rpview") >= 1
    # late record (GRACE 0: dropped) — the cached serve stays exact vs
    # the uncached pipeline (compared pre-wire, where types match)
    _append(stub, "rpsrc", [{"city": "sf"}], [BASE + 1000])
    mat = ctx.views.get("rpview")
    sel = _pull("SELECT * FROM rpview;")
    deadline = time.time() + 10
    while time.time() < deadline:
        cached, _how, _x = ctx.read_cache.serve_view(
            "rpview", mat, sel, "SELECT * FROM rpview;")
        direct = serve_select_view(mat, sel)
        if _canon(cached) == _canon(direct):
            break
        time.sleep(0.2)
    assert _canon(cached) == _canon(direct)
    closed3 = {r["city"]: r["c"] for r in cached
               if r.get("winStart") == BASE}
    assert closed3.get("la") == 2  # late row did not corrupt the close


def test_drop_view_invalidates_server_cache(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="dvsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW dview AS SELECT city, COUNT(*) AS c "
                  "FROM dvsrc GROUP BY city, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-dview")
    stub.ExecuteQuery(pb.CommandQuery(stmt_text="SELECT * FROM dview;"))
    stub.ExecuteQuery(pb.CommandQuery(stmt_text="DROP VIEW dview;"))
    assert all(k[1] != "dview" for k in ctx.read_cache._entries
               if k[0] == "snap")


# ---- concurrent readers under the lock-order witness ------------------------


def test_concurrent_readers_exact_and_cycle_free():
    """N readers hammer the cache while a mutator closes windows under
    the task lock: every served snapshot equals the uncached pipeline
    at SOME committed version (no torn reads, no stale hits), and the
    armed witness sees zero lock cycles."""
    LOCKTRACE.disarm()
    LOCKTRACE.arm()
    try:
        ex = _FakeEx()
        mat = _view(ex)
        sel = _pull("SELECT * FROM v;")
        cache = ReadCache()
        canon_lock = threading.Lock()
        canonical: set[str] = set()

        def commit(row):
            # mutate + record the canonical answer atomically (the
            # same state_lock the read path takes)
            with mat.task.state_lock:
                mat.add_closed([row])
                ex.ver += 1
                with canon_lock:
                    canonical.add(_canon(serve_select_view(mat, sel)))

        with canon_lock:
            canonical.add(_canon(serve_select_view(mat, sel)))
        stop = threading.Event()
        errors: list[str] = []

        def reader():
            while not stop.is_set():
                rows, how, _ = cache.serve_view("v", mat, sel, "q")
                got = _canon(rows)
                with canon_lock:
                    ok = got in canonical
                if not ok:
                    errors.append(f"{how}: {got[:120]}")
                    return

        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(60):
            commit({"k": f"k{i % 7}", "c": i, "winStart": BASE + i * 10,
                    "winEnd": BASE + i * 10 + 10_000})
            time.sleep(0.002)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors[:3]
        assert LOCKTRACE.cycles() == []
        st = cache.stats()
        assert st["hits"] + st["shared"] + st["misses"] > 0
    finally:
        LOCKTRACE.disarm()
