"""SQL frontend tests: parse -> refine -> codegen -> execution.

Golden SQL->plan checks plus end-to-end runs of lowered plans, mirroring
the reference's ParseRefineSpec / Codegen specs (hstream-sql/test)."""

import pytest

from hstream_tpu.common.errors import SQLValidateError
from hstream_tpu.engine.plan import AggKind, AggregateNode, FilterNode
from hstream_tpu.engine.window import (
    HoppingWindow,
    SessionWindow,
    TumblingWindow,
)
from hstream_tpu.sql import parse_and_refine, plans, stream_codegen
from hstream_tpu.sql.codegen import bind_schema, explain_text, make_executor

BASE = 1_700_000_000_000


def test_parse_refine_select():
    stmt = parse_and_refine(
        "SELECT COUNT(*), SUM(temp) FROM weather "
        "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    assert stmt.source.name == "weather"
    assert stmt.emit_changes


def test_codegen_tumbling_plan():
    plan = stream_codegen(
        "SELECT COUNT(*), SUM(temp) FROM weather WHERE temp > 0 "
        "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    assert isinstance(plan, plans.SelectPlan)
    node = plan.node
    assert isinstance(node, AggregateNode)
    assert isinstance(node.window, TumblingWindow)
    assert node.window.size_ms == 10_000
    assert [a.kind for a in node.aggs] == [AggKind.COUNT_ALL, AggKind.SUM]
    assert isinstance(node.child, FilterNode)
    assert node.post_projections == []  # natural emission


def test_codegen_hopping_and_session():
    p1 = stream_codegen(
        "SELECT AVG(x) FROM s GROUP BY k, "
        "HOPPING (INTERVAL 1 MINUTE, INTERVAL 10 SECOND) EMIT CHANGES;")
    assert isinstance(p1.node.window, HoppingWindow)
    assert p1.node.window.advance_ms == 10_000
    p2 = stream_codegen(
        "SELECT COUNT(*) FROM s GROUP BY k, "
        "SESSION (INTERVAL 30 SECOND) EMIT CHANGES;")
    assert isinstance(p2.node.window, SessionWindow)
    assert p2.node.window.gap_ms == 30_000


def test_codegen_plan_types():
    assert isinstance(stream_codegen("CREATE STREAM s;"), plans.CreatePlan)
    assert isinstance(
        stream_codegen("CREATE STREAM s2 AS SELECT COUNT(*) FROM s "
                       "GROUP BY k EMIT CHANGES;"),
        plans.CreateBySelectPlan)
    assert isinstance(
        stream_codegen("CREATE VIEW v AS SELECT COUNT(*) FROM s "
                       "GROUP BY k;"), plans.CreateViewPlan)
    p = stream_codegen("INSERT INTO s (a, b) VALUES (1, 'x');")
    assert isinstance(p, plans.InsertPlan)
    assert p.payload == {"a": 1, "b": "x"}
    pj = stream_codegen('INSERT INTO s VALUES \'{"a": 2.5}\';')
    assert pj.payload == {"a": 2.5}
    assert isinstance(stream_codegen("SHOW STREAMS;"), plans.ShowPlan)
    assert isinstance(stream_codegen("DROP VIEW v IF EXISTS;"),
                      plans.DropPlan)
    assert isinstance(stream_codegen("TERMINATE QUERY q1;"),
                      plans.TerminatePlan)
    sv = stream_codegen("SELECT * FROM v WHERE k = 'a';")
    assert isinstance(sv, plans.SelectViewPlan)
    ex = stream_codegen("EXPLAIN SELECT COUNT(*) FROM s GROUP BY k "
                        "EMIT CHANGES;")
    assert isinstance(ex, plans.ExplainPlan)
    assert "AGGREGATE" in ex.text and "SOURCE" in ex.text


def test_validate_errors():
    with pytest.raises(SQLValidateError):
        parse_and_refine("SELECT COUNT(*) FROM s WHERE SUM(x) > 1 "
                         "GROUP BY k EMIT CHANGES;")
    with pytest.raises(SQLValidateError):
        parse_and_refine("SELECT x AS a, y AS a FROM s EMIT CHANGES;")
    with pytest.raises(SQLValidateError):
        parse_and_refine("SELECT SUM(COUNT(*)) FROM s GROUP BY k "
                         "EMIT CHANGES;")
    with pytest.raises(SQLValidateError):
        parse_and_refine(
            "SELECT * FROM s GROUP BY k, HOPPING (INTERVAL 15 SECOND, "
            "INTERVAL 10 SECOND) EMIT CHANGES;")


def run_sql(sql, batches):
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=batches[0][0], initial_keys=8,
                       batch_capacity=256)
    out = []
    for rows, ts in batches:
        out.extend(ex.process(rows, ts))
    return ex, out


def test_sql_end_to_end_tumbling():
    rows1 = [{"city": "sf", "temp": 10.0}, {"city": "sf", "temp": 20.0},
             {"city": "la", "temp": 30.0}]
    closer = [{"city": "la", "temp": 1.0}]
    _, out = run_sql(
        "SELECT COUNT(*), SUM(temp) FROM weather "
        "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 1 SECOND EMIT CHANGES;",
        [(rows1, [BASE, BASE + 100, BASE + 200]),
         (closer, [BASE + 20_000])])
    got = {(r["city"], r.get("winStart")): r for r in out}
    assert got[("sf", BASE)]["COUNT(*)"] == 2
    assert got[("sf", BASE)]["SUM(temp)"] == pytest.approx(30.0)


def test_sql_end_to_end_projection_alias():
    rows1 = [{"city": "sf", "temp": 10.0}, {"city": "sf", "temp": 30.0}]
    closer = [{"city": "x", "temp": 0.0}]
    _, out = run_sql(
        "SELECT city, AVG(temp) AS avg_temp, SUM(temp) / COUNT(temp) AS "
        "check FROM weather GROUP BY city, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;",
        [(rows1, [BASE, BASE + 100]), (closer, [BASE + 20_000])])
    sf = [r for r in out if r.get("city") == "sf"]
    assert len(sf) >= 1
    assert sf[-1]["avg_temp"] == pytest.approx(20.0)
    assert sf[-1]["check"] == pytest.approx(20.0)


def test_sql_having():
    rows1 = [{"k": "a", "x": 1.0}, {"k": "a", "x": 1.0},
             {"k": "b", "x": 1.0}]
    closer = [{"k": "c", "x": 0.0}]
    _, out = run_sql(
        "SELECT k, COUNT(*) AS c FROM s GROUP BY k, "
        "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
        "HAVING COUNT(*) >= 2 EMIT CHANGES;",
        [(rows1, [BASE, BASE + 1, BASE + 2]), (closer, [BASE + 20_000])])
    ks = {r["k"] for r in out}
    assert "a" in ks and "b" not in ks


def test_sql_stateless_select():
    _, out = run_sql(
        "SELECT temp AS t, city FROM weather WHERE temp > 15 EMIT CHANGES;",
        [([{"city": "sf", "temp": 10.0}, {"city": "la", "temp": 20.0}],
          [BASE, BASE + 1])])
    assert out == [{"t": 20.0, "city": "la"}]


def test_sql_aliased_group_key_not_duplicated():
    rows = [{"city": "sf", "temp": 1.0}, {"city": "sf", "temp": 2.0}]
    closer = [{"city": "xx", "temp": 0.0}]
    _, out = run_sql(
        "SELECT city AS town, COUNT(*) AS c FROM weather "
        "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;",
        [(rows, [BASE, BASE + 1]), (closer, [BASE + 20_000])])
    sf = [r for r in out if r.get("town") == "sf"]
    assert sf and sf[-1]["c"] == 2
    assert "city" not in sf[-1]  # alias renames, no duplicate key column


def test_sql_string_filter_on_device():
    rows = [{"city": "sf", "temp": 1.0}, {"city": "la", "temp": 1.0},
            {"city": "sf", "temp": 1.0}]
    closer = [{"city": "xx", "temp": 0.0}]
    _, out = run_sql(
        "SELECT COUNT(*) AS c FROM weather WHERE city = 'sf' "
        "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;",
        [(rows, [BASE, BASE + 1, BASE + 2]), (closer, [BASE + 20_000])])
    assert any(r["c"] == 2 and r["city"] == "sf" for r in out)
    assert not any(r.get("city") == "la" for r in out)


def test_session_window_end_to_end():
    sql = ("SELECT k, COUNT(*) AS c FROM s GROUP BY k, "
           "SESSION (INTERVAL 5 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"k": "a"}])
    # two bursts for key a separated by > gap -> two sessions
    ex.process([{"k": "a"}, {"k": "a"}], [BASE, BASE + 1000])
    ex.process([{"k": "a"}], [BASE + 10_000])
    out = ex.process([{"k": "a"}], [BASE + 30_000])  # closes both
    wins = {(r["winStart"], r["winEnd"]): r["c"] for r in out}
    assert wins[(BASE, BASE + 1000 + 5000)] == 2
    assert wins[(BASE + 10_000, BASE + 15_000)] == 1


def test_session_merge_on_overlap():
    sql = ("SELECT k, COUNT(*) AS c, MIN(x) AS mn FROM s GROUP BY k, "
           "SESSION (INTERVAL 5 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"k": "a", "x": 1.0}])
    # records at 0 and 8s: separate sessions; then 4s bridges them
    ex.process([{"k": "a", "x": 3.0}], [BASE])
    ex.process([{"k": "a", "x": 5.0}], [BASE + 8000])
    ex.process([{"k": "a", "x": 1.0}], [BASE + 4000])
    out = ex.process([{"k": "a", "x": 9.0}], [BASE + 40_000])
    big = [r for r in out if r["c"] == 3]
    assert len(big) == 1
    assert big[0]["winStart"] == BASE
    assert big[0]["winEnd"] == BASE + 8000 + 5000
    assert big[0]["mn"] == pytest.approx(1.0)


def test_session_approx_quantile():
    import numpy as np

    sql = ("SELECT k, APPROX_QUANTILE(x, 0.5) AS p50 FROM s GROUP BY k, "
           "SESSION (INTERVAL 5 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"k": "a", "x": 1.0}])
    rng = np.random.default_rng(0)
    vals = rng.lognormal(1.0, 0.8, size=500)
    rows = [{"k": "a", "x": float(v)} for v in vals]
    ex.process(rows, [BASE + i for i in range(500)])
    out = ex.process([{"k": "a", "x": 1.0}], [BASE + 60_000])
    true = float(np.quantile(vals, 0.5))
    assert out and abs(out[0]["p50"] - true) / true < 0.1


def test_bind_schema_inference():
    plan = stream_codegen(
        "SELECT COUNT(*), SUM(temp) FROM weather WHERE city = 'sf' "
        "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    schema = bind_schema(plan)
    from hstream_tpu.engine.types import ColumnType

    assert schema.type_of("temp") == ColumnType.FLOAT
    assert schema.type_of("city") == ColumnType.STRING
