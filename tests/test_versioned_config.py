"""CAS-versioned config store (reference hs_versioned_config_store.cpp)
+ its boot-epoch consumer."""

import threading

import pytest

from hstream_tpu.store import open_store
from hstream_tpu.store.native import NativeLogStore
from hstream_tpu.store.versioned import VersionedConfigStore, VersionMismatch


def test_create_update_delete_cycle():
    vcs = VersionedConfigStore(open_store("mem://"))
    assert vcs.get("a") is None
    assert vcs.put("a", b"v1") == 1
    assert vcs.get("a") == (1, b"v1")
    with pytest.raises(VersionMismatch):
        vcs.put("a", b"again")          # create on existing
    with pytest.raises(VersionMismatch):
        vcs.put("a", b"x", base_version=7)  # wrong base
    assert vcs.put("a", b"v2", base_version=1) == 2
    assert vcs.get("a") == (2, b"v2")
    with pytest.raises(VersionMismatch):
        vcs.delete("a", base_version=1)
    vcs.delete("a", base_version=2)
    assert vcs.get("a") is None
    # re-create after delete continues the version chain (tombstone CAS)
    assert vcs.put("a", b"v3") == 4
    vcs.delete("a", base_version=4)
    vcs.put("x", b"1")
    vcs.put("y", b"2")
    assert vcs.keys() == ["x", "y"]


def test_concurrent_cas_single_winner_per_round():
    store = open_store("mem://")
    vcs = VersionedConfigStore(store)
    vcs.put("c", b"0")
    wins, losses = [], []
    barrier = threading.Barrier(8)

    def bump(t):
        barrier.wait(5)
        for _ in range(50):
            cur = vcs.get("c")
            try:
                vcs.put("c", str(int(cur[1]) + 1).encode(),
                        base_version=cur[0])
                wins.append(t)
            except VersionMismatch:
                losses.append(t)

    threads = [threading.Thread(target=bump, args=(t,)) for t in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(30)
    version, value = vcs.get("c")
    # every applied write bumped the version AND the counter exactly once
    assert version == 1 + len(wins)
    assert int(value) == len(wins)


def test_versions_survive_native_reopen(tmp_path):
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    vcs = VersionedConfigStore(store)
    vcs.put("cfg", b"one")
    vcs.put("cfg", b"two", base_version=1)
    store.close()
    re = NativeLogStore(root)
    assert VersionedConfigStore(re).get("cfg") == (2, b"two")
    re.close()


def test_boot_epoch_increments_across_server_boots(tmp_path):
    from hstream_tpu.server.main import serve

    store_dir = str(tmp_path / "store")
    for expected in (1, 2, 3):
        server, ctx = serve("127.0.0.1", 0, store_dir)
        assert ctx.boot_epoch == expected
        server.stop(grace=1)
        ctx.shutdown()
