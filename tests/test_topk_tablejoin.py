"""TOPK/TOPKDISTINCT aggregates (reference AST.hs:107-120) and
stream-table join (reference Stream.hs:302-344) — VERDICT item 9."""

import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.engine.snapshot import restore_executor, snapshot_executor
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached
from hstream_tpu.sql.codegen import make_executor, stream_codegen

BASE = 1_700_000_000_000


def _run(sql, batches, sample):
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=sample)
    out = []
    for b in batches:
        out.extend(ex.process(*b))
    return out, ex


# ---- TOPK -------------------------------------------------------------------


def test_topk_device_lattice():
    rows = [{"d": "a", "v": float(x)} for x in [5, 1, 9, 7, 3, 9]]
    rows += [{"d": "b", "v": 2.0}]
    out, _ = _run(
        "SELECT d, TOPK(v, 3) AS top FROM s GROUP BY d, "
        "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
        "EMIT CHANGES;",
        [(rows, [BASE + i for i in range(7)]),
         ([{"d": "z", "v": 0.0}], [BASE + 30_000])],
        [{"d": "a", "v": 1.0}])
    fin = {r["d"]: r["top"] for r in out if r.get("winStart") == BASE}
    assert fin["a"] == [9.0, 9.0, 7.0]   # duplicates kept
    assert fin["b"] == [2.0]             # short groups pad-free


def test_topk_distinct_device_lattice():
    rows = [{"d": "a", "v": float(x)} for x in [5, 9, 9, 9, 7, 5, 3]]
    out, _ = _run(
        "SELECT d, TOPKDISTINCT(v, 3) AS top FROM s GROUP BY d, "
        "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
        "EMIT CHANGES;",
        [(rows, [BASE + i for i in range(7)]),
         ([{"d": "z", "v": 0.0}], [BASE + 30_000])],
        [{"d": "a", "v": 1.0}])
    fin = {r["d"]: r["top"] for r in out if r.get("winStart") == BASE}
    assert fin["a"] == [9.0, 7.0, 5.0]


def test_topk_across_batches_monoid():
    """Top-k folds across micro-batches: later batches can evict."""
    out, _ = _run(
        "SELECT d, TOPK(v, 2) AS top FROM s GROUP BY d, "
        "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
        "EMIT CHANGES;",
        [([{"d": "a", "v": 1.0}, {"d": "a", "v": 5.0}], [BASE, BASE + 1]),
         ([{"d": "a", "v": 3.0}], [BASE + 2]),
         ([{"d": "a", "v": 8.0}], [BASE + 3]),
         ([{"d": "z", "v": 0.0}], [BASE + 30_000])],
        [{"d": "a", "v": 1.0}])
    fin = [r["top"] for r in out
           if r.get("winStart") == BASE and r["d"] == "a"]
    assert fin[-1] == [8.0, 5.0]


def test_topk_k1_and_explain_and_table_named_stream():
    """Regression trio: k=1 must not break the packed drain layout;
    EXPLAIN renders table joins; a stream literally named 'table' still
    works in interval joins."""
    out, _ = _run(
        "SELECT d, TOPK(v, 1) AS top FROM s GROUP BY d, "
        "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
        "EMIT CHANGES;",
        [([{"d": "a", "v": 5.0}, {"d": "a", "v": 7.0}], [BASE, BASE + 1])],
        [{"d": "a", "v": 1.0}])
    assert [r["top"] for r in out if r.get("d") == "a"][-1] == [7.0]
    p = stream_codegen(
        "EXPLAIN SELECT l.a, COUNT(*) FROM s1 AS l INNER JOIN "
        "TABLE(s2) AS r ON l.a = r.k GROUP BY l.a EMIT CHANGES;")
    assert "JOIN TABLE(s2)" in p.text
    p2 = stream_codegen(
        "SELECT COUNT(*) FROM s1 AS l INNER JOIN table AS t "
        "WITHIN (INTERVAL 1 SECOND) ON l.k = t.k GROUP BY l.k "
        "EMIT CHANGES;")
    assert p2.join.table is False and p2.join.within.ms == 1000


def test_topk_session_host_engine():
    out, _ = _run(
        "SELECT u, TOPK(v, 2) AS top FROM s GROUP BY u, "
        "SESSION (INTERVAL 5 SECOND) GRACE BY INTERVAL 0 SECOND "
        "EMIT CHANGES;",
        [([{"u": "x", "v": 1.0}, {"u": "x", "v": 7.0},
           {"u": "x", "v": 4.0}], [BASE, BASE + 10, BASE + 20]),
         ([{"u": "zz", "v": 0.0}], [BASE + 60_000])],
        [{"u": "x", "v": 1.0}])
    fin = [r for r in out if r.get("u") == "x"]
    assert fin[-1]["top"] == [7.0, 4.0]


def test_topk_snapshot_roundtrip():
    sql = ("SELECT d, TOPK(v, 2) AS top FROM s GROUP BY d, "
           "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"d": "a", "v": 1.0}])
    ex.process([{"d": "a", "v": 5.0}, {"d": "a", "v": 2.0}],
               [BASE, BASE + 1])
    blob = snapshot_executor(ex)
    re, _ = restore_executor(plan, blob)
    out = re.process([{"d": "a", "v": 4.0}], [BASE + 2])
    out += re.process([{"d": "z", "v": 0.0}], [BASE + 30_000])
    fin = [r["top"] for r in out
           if r.get("winStart") == BASE and r.get("d") == "a"]
    assert fin[-1] == [5.0, 4.0]


# ---- stream-table join ------------------------------------------------------


def test_table_join_engine():
    sql = ("SELECT o.item, SUM(o.qty) AS q FROM orders AS o "
           "INNER JOIN TABLE(prices) AS p ON o.item = p.item "
           "GROUP BY o.item, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"item": "x", "qty": 1.0}])
    # stream rows before any table row: dropped (INNER)
    out = ex.process([{"item": "x", "qty": 1.0}], [BASE], stream="orders")
    assert out == []
    # table rows update state, emit nothing
    out = ex.process([{"item": "x", "price": 10.0}], [BASE + 1],
                     stream="prices")
    assert out == []
    out = ex.process([{"item": "x", "qty": 2.0},
                      {"item": "y", "qty": 9.0}],
                     [BASE + 2, BASE + 3], stream="orders")
    out += ex.process([{"item": "x", "qty": 3.0}], [BASE + 4],
                      stream="o")  # alias routes too
    out += ex.process([{"item": "zz", "qty": 0.0}], [BASE + 30_000],
                      stream="orders")
    fin = {r["o.item"]: r["q"] for r in out if r.get("winStart") == BASE}
    # y had no table row -> dropped; x: 2 + 3 (first x was pre-table)
    assert fin == {"x": pytest.approx(5.0)}
    # joined rows carry both sides' fields
    assert ex.table[("x",)][1]["price"] == 10.0


def test_table_join_last_value_wins():
    sql = ("SELECT s.k, MAX(s.v) AS m FROM s "
           "INNER JOIN TABLE(t) ON s.k = t.k GROUP BY s.k "
           "EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"k": "a", "v": 1.0}])
    ex.process([{"k": "a", "tag": "old"}], [BASE], stream="t")
    ex.process([{"k": "a", "tag": "new"}], [BASE + 10], stream="t")
    # out-of-order older update must NOT clobber the newer one
    ex.process([{"k": "a", "tag": "stale"}], [BASE + 5], stream="t")
    assert ex.table[("a",)][1]["tag"] == "new"


def test_table_join_snapshot_roundtrip():
    sql = ("SELECT s.k, COUNT(*) AS c FROM s "
           "INNER JOIN TABLE(t) ON s.k = t.k GROUP BY s.k, "
           "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=[{"k": "a"}])
    ex.process([{"k": "a", "side": "table"}], [BASE], stream="t")
    ex.process([{"k": "a"}], [BASE + 1], stream="s")
    blob = snapshot_executor(ex)
    re, _ = restore_executor(plan, blob)
    out = re.process([{"k": "a"}], [BASE + 2], stream="s")
    out += re.process([{"k": "zz"}], [BASE + 30_000], stream="s")
    fin = [r["c"] for r in out
           if r.get("winStart") == BASE and r.get("s.k") == "a"]
    assert fin[-1] == 2  # 1 before snapshot + 1 after


def test_table_join_through_server():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="ord"))
        stub.CreateStream(pb.Stream(stream_name="prc"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW tj AS SELECT ord.item, COUNT(*) AS c "
                      "FROM ord INNER JOIN TABLE(prc) "
                      "ON ord.item = prc.item GROUP BY ord.item, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        wait_attached(ctx, "view-tj")
        req = pb.AppendRequest(stream_name="prc")
        req.records.append(rec.build_record({"item": "x", "price": 2.0},
                                            publish_time_ms=BASE))
        stub.Append(req)
        time.sleep(0.3)  # table row lands before the stream rows
        req = pb.AppendRequest(stream_name="ord")
        for i in range(3):
            req.records.append(rec.build_record(
                {"item": "x"}, publish_time_ms=BASE + 10 + i))
        req.records.append(rec.build_record(
            {"item": "nope"}, publish_time_ms=BASE + 20))
        stub.Append(req)
        req = pb.AppendRequest(stream_name="ord")
        req.records.append(rec.build_record({"item": "zz"},
                                            publish_time_ms=BASE + 30_000))
        stub.Append(req)
        deadline = time.time() + 30
        rows = []
        while time.time() < deadline:
            resp = stub.ExecuteQuery(pb.CommandQuery(
                stmt_text="SELECT * FROM tj;"))
            rows = [rec.struct_to_dict(s) for s in resp.result_set]
            if any(r.get("c") == 3 for r in rows
                   if r.get("winStart") == BASE):
                break
            time.sleep(0.2)
        closed = {r["ord.item"]: r["c"] for r in rows
                  if r.get("winStart") == BASE}
        assert closed == {"x": 3}, rows  # 'nope' had no table row
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_topk_through_server_view():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="tks"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW tkv AS SELECT d, TOPK(v, 2) AS top "
                      "FROM tks GROUP BY d, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        wait_attached(ctx, "view-tkv")
        req = pb.AppendRequest(stream_name="tks")
        for i, v in enumerate([3.0, 9.0, 5.0]):
            req.records.append(rec.build_record(
                {"d": "a", "v": v}, publish_time_ms=BASE + i))
        req.records.append(rec.build_record(
            {"d": "z", "v": 0.0}, publish_time_ms=BASE + 30_000))
        stub.Append(req)
        deadline = time.time() + 30
        rows = []
        while time.time() < deadline:
            resp = stub.ExecuteQuery(pb.CommandQuery(
                stmt_text="SELECT * FROM tkv;"))
            rows = [rec.struct_to_dict(s) for s in resp.result_set]
            if any(r.get("d") == "a" and r.get("winStart") == BASE
                   for r in rows):
                break
            time.sleep(0.2)
        got = [r["top"] for r in rows
               if r.get("d") == "a" and r.get("winStart") == BASE]
        assert got and got[0] == [9.0, 5.0], rows
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()
