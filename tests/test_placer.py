"""The placer (ISSUE 17): scoring units, heartbeat/offer/adopt CAS
discipline, and the seeded in-process 3-node acceptance — least-loaded
placement, live failover adoption of a killed node's queries, rebalance
on load skew — over ONE shared in-memory store (the CI placer smoke).

Runtime-budgeted: fast knobs everywhere (placer tick 100ms, heartbeat
lease <= 1s), whole file well under 60s on the CPU backend.
"""

from __future__ import annotations

import json
import time

import grpc

from hstream_tpu.common import records as rec
from hstream_tpu.placer.score import (
    SKIP_FENCED,
    SKIP_SHEDDING,
    SKIP_STALE,
    SKIP_STALLED,
    node_score,
    rank_nodes,
    skip_reason,
)
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server import scheduler
from hstream_tpu.server.context import ServerContext
from hstream_tpu.server.main import serve
from hstream_tpu.server.persistence import TaskStatus
from hstream_tpu.store import open_store

BASE = 1_700_000_000_000
NOW = 10**14  # fixed "now" for pure scoring units


def _wait(cond, timeout=20.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


# ---- scoring units ----------------------------------------------------------


def test_node_score_folds_load_axes():
    idle = {"hb_ms": NOW}
    assert node_score(idle) == 0.0
    loaded = {"hb_ms": NOW, "running_queries": 3, "append_inflight": 2,
              "append_front": {"in_flight": 1},
              "arena_pending_batches": 2,
              "dispatch_p99_ms": 7.5, "rss_bytes": 2_000_000_000,
              "health": {"degraded": 1, "stalled": 0}}
    # 3*10 + 2*2 + 1*2 + 2*2 + 7.5 + 2 + 10 = 59.5
    assert node_score(loaded) == 59.5
    # a stalled query dominates any realistic load delta
    assert node_score({"health": {"stalled": 1}}) == 100.0


def test_skip_reasons_cover_ineligible_nodes():
    lease = 1000
    ok = {"hb_ms": NOW}
    assert skip_reason(ok, lease_ms=lease, now_ms=NOW) is None
    stale = {"hb_ms": NOW - 5000}
    assert skip_reason(stale, lease_ms=lease, now_ms=NOW) == SKIP_STALE
    fenced = {"hb_ms": NOW, "fenced": True}
    assert skip_reason(fenced, lease_ms=lease, now_ms=NOW) == SKIP_FENCED
    shed = {"hb_ms": NOW, "shed_level": 2}
    assert skip_reason(shed, lease_ms=lease, now_ms=NOW) == SKIP_SHEDDING
    sick = {"hb_ms": NOW, "health": {"stalled": 2}}
    assert skip_reason(sick, lease_ms=lease, now_ms=NOW) == SKIP_STALLED
    # precedence: a stale record is skipped as stale even if also fenced
    assert skip_reason({"hb_ms": NOW - 5000, "fenced": True},
                       lease_ms=lease, now_ms=NOW) == SKIP_STALE


def test_rank_nodes_is_deterministic_with_name_tiebreak():
    records = {
        "b-node": {"hb_ms": NOW, "running_queries": 1},
        "a-node": {"hb_ms": NOW, "running_queries": 1},
        "c-busy": {"hb_ms": NOW, "running_queries": 5},
        "d-dead": {"hb_ms": NOW - 10_000},
    }
    ranked, skipped = rank_nodes(records, lease_ms=1000, now_ms=NOW)
    # equal scores tie-break on the node name; the busy node ranks last
    assert [n for _s, n in ranked] == ["a-node", "b-node", "c-busy"]
    assert skipped == {"d-dead": SKIP_STALE}


# ---- heartbeat / offer / live-adopt CAS units -------------------------------


def _two_contexts():
    """Two bare server contexts over ONE store + persistence: ctx2
    boots later, so its epoch is strictly higher. Both placers are
    ARMED (their records carry heartbeats — a disarmed server writes
    legacy epoch-only records) but never started: no background ticks,
    tests drive the stages directly."""
    store = open_store("mem://")
    ctx1 = ServerContext(store, port=1111, owns_store=False,
                         placer_interval_ms=100)
    ctx2 = ServerContext(store, persistence=ctx1.persistence, port=2222,
                         owns_store=False, placer_interval_ms=100)
    assert ctx2.boot_epoch > ctx1.boot_epoch
    return store, ctx1, ctx2


def _rewrite_hb(ctx, qid, hb_ms):
    """Backdate a record's heartbeat (simulates a crashed owner whose
    last stamp is old)."""
    key = "scheduler/query/" + qid
    version, raw = ctx.config.get(key)
    record = json.loads(raw)
    record["hb_ms"] = hb_ms
    ctx.config.put(key, json.dumps(record).encode(), base_version=version)


def test_record_assignment_carries_heartbeat_and_refreshes():
    store, ctx1, ctx2 = _two_contexts()
    try:
        scheduler.record_assignment(ctx1, "q1")
        a = scheduler.assignment(ctx1, "q1")
        assert a["state"] == "owned"
        assert scheduler.owner_live(a, lease_ms=10_000)
        _rewrite_hb(ctx1, "q1", scheduler.now_ms() - 60_000)
        assert not scheduler.owner_live(scheduler.assignment(ctx1, "q1"),
                                        lease_ms=10_000)
        # the owner's heartbeat refreshes the stamp...
        assert scheduler.heartbeat_assignment(ctx1, "q1")
        assert scheduler.owner_live(scheduler.assignment(ctx1, "q1"),
                                    lease_ms=10_000)
        # ...but a non-owner's heartbeat refuses without writing
        before = scheduler.assignment(ctx1, "q1")
        assert not scheduler.heartbeat_assignment(ctx2, "q1")
        assert scheduler.assignment(ctx1, "q1") == before
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_try_adopt_live_respects_fresh_heartbeat_whatever_epoch():
    store, ctx1, ctx2 = _two_contexts()
    try:
        scheduler.record_assignment(ctx1, "q1")
        # ctx2's epoch is higher, but ctx1's heartbeat is FRESH: the
        # live-peer regression pin — never adopted, never re-placed
        assert not scheduler.try_adopt_live(ctx2, "q1", lease_ms=5000)
        assert scheduler.assignment(ctx2, "q1")["node"] \
            == scheduler.node_name(ctx1)
        # once the lease lapses the survivor takes it
        _rewrite_hb(ctx1, "q1", scheduler.now_ms() - 60_000)
        assert scheduler.try_adopt_live(ctx2, "q1", lease_ms=5000)
        a = scheduler.assignment(ctx2, "q1")
        assert a["node"] == scheduler.node_name(ctx2)
        assert a["state"] == "owned"
        # adoption journaled with the machine-readable previous owner
        kinds = [e["kind"] for e in ctx2.events.query(limit=100)]
        assert "query_adopted" in kinds
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_try_adopt_live_claims_missing_and_offered_records():
    store, ctx1, ctx2 = _two_contexts()
    try:
        # missing record: claimable outright
        assert scheduler.try_adopt_live(ctx2, "orphan", lease_ms=5000)
        assert scheduler.assignment(ctx2, "orphan")["node"] \
            == scheduler.node_name(ctx2)
        # an offer names its target: the target claims it despite the
        # offer's fresh heartbeat; anyone else must wait out the lease
        scheduler.record_assignment(ctx1, "q2")
        assert scheduler.offer_assignment(
            ctx1, "q2", scheduler.node_name(ctx2))
        offered = scheduler.assignment(ctx1, "q2")
        assert offered["state"] == "offered"
        assert offered["epoch"] == 0
        assert offered["src"] == scheduler.node_name(ctx1)
        assert not scheduler.try_adopt_live(ctx1, "q2", lease_ms=5000)
        assert scheduler.try_adopt_live(ctx2, "q2", lease_ms=5000)
        assert scheduler.assignment(ctx2, "q2")["state"] == "owned"
        # already mine: nothing to adopt
        assert not scheduler.try_adopt_live(ctx2, "q2", lease_ms=5000)
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_try_adopt_live_legacy_records_keep_epoch_rule():
    store, ctx1, ctx2 = _two_contexts()
    try:
        legacy_hi = json.dumps({"node": "server-9@x:1",
                                "epoch": ctx2.boot_epoch + 5}).encode()
        ctx1.config.put("scheduler/query/qh", legacy_hi)
        assert not scheduler.try_adopt_live(ctx2, "qh", lease_ms=100)
        legacy_lo = json.dumps({"node": "server-9@x:1",
                                "epoch": 1}).encode()
        ctx2.config.put("scheduler/query/ql", legacy_lo)
        assert scheduler.try_adopt_live(ctx2, "ql", lease_ms=100)
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_boot_try_adopt_stays_epoch_only():
    """The disarmed/boot path is untouched by heartbeats: a stale-epoch
    record is adopted even though its launch-time hb_ms is fresh."""
    store, ctx1, ctx2 = _two_contexts()
    try:
        scheduler.record_assignment(ctx1, "q1")  # fresh hb_ms
        assert scheduler.try_adopt(ctx2, "q1")
        assert scheduler.assignment(ctx2, "q1")["node"] \
            == scheduler.node_name(ctx2)
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_disarmed_server_writes_legacy_record():
    """A server with the placer disarmed writes the legacy two-field
    record: it will never refresh a heartbeat, and a launch-time stamp
    it can't refresh would read as a lapsed lease to every armed peer
    one lease later — live-adopting a query whose disarmed owner is
    alive and running (rolling placer enablement)."""
    store = open_store("mem://")
    ctx1 = ServerContext(store, port=1111, owns_store=False)  # disarmed
    try:
        scheduler.record_assignment(ctx1, "q1")
        a = scheduler.assignment(ctx1, "q1")
        assert "hb_ms" not in a and "state" not in a
        # never judged by the lease: health/adoption fall back to the
        # pure epoch rule instead of misreading a stale stamp
        assert scheduler.owner_heartbeat_age_ms(a) is None
        assert not scheduler.owner_live(a, lease_ms=10_000)
    finally:
        ctx1.shutdown()
        store.close()


def test_adopt_sweep_never_takes_a_live_disarmed_peers_query():
    """The LIVE sweep must not apply the boot-epoch rule to a legacy
    record: its (disarmed) owner never heartbeats, so a lower epoch
    does not mean it is dead — only boot-time adoption (where the
    predecessor on the same store really is gone) may claim it."""
    from hstream_tpu.server.persistence import QueryInfo

    store, ctx1, ctx2 = _two_contexts()
    try:
        ctx1.persistence.insert_query(QueryInfo(
            query_id="q1", sql="select", created_time_ms=BASE,
            query_type="stream", status=TaskStatus.CREATED, sink="s"))
        ctx1.persistence.set_query_status("q1", TaskStatus.RUNNING)
        legacy = json.dumps({"node": "server-9@x:1",
                             "epoch": 1}).encode()
        ctx1.config.put("scheduler/query/q1", legacy)
        ctx2.placer._adopt_sweep()  # epoch 1 << ctx2's, still skipped
        assert scheduler.assignment(ctx2, "q1")["node"] == "server-9@x:1"
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_orphaned_created_query_rescued_after_lease_lapse():
    """place_for_launch's offer names a target that dies before
    claiming: once the offer's heartbeat lapses, ANY survivor's sweep
    rescues the CREATED query — it must not wait for a server reboot
    while the cluster is live."""
    from hstream_tpu.server.persistence import QueryInfo

    store, ctx1, ctx2 = _two_contexts()
    try:
        ctx1.persistence.insert_query(QueryInfo(
            query_id="q1", sql="select", created_time_ms=BASE,
            query_type="stream", status=TaskStatus.CREATED, sink="s"))
        offer = {"node": "server-9@x:1", "epoch": 0,
                 "hb_ms": scheduler.now_ms(), "state": "offered",
                 "src": scheduler.node_name(ctx1)}
        ctx1.config.put("scheduler/query/q1",
                        json.dumps(offer).encode())
        # offer FRESH: the query stays the target's to claim
        ctx2.placer._adopt_sweep()
        assert scheduler.assignment(ctx2, "q1")["node"] == "server-9@x:1"
        # the target died without claiming: its offer lapses
        _rewrite_hb(ctx2, "q1", scheduler.now_ms() - 60_000)
        ctx2.placer._adopt_sweep()
        a = scheduler.assignment(ctx2, "q1")
        assert a["node"] == scheduler.node_name(ctx2)
        assert a["state"] == "owned"
        adopts = [d for d in ctx2.placer.status()["decisions"]
                  if d["action"] == "adopt"]
        assert adopts and adopts[-1]["query"] == "q1"
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


def test_lease_clamped_to_three_ticks():
    """An interval larger than the lease would make every healthy
    owner look dead between heartbeats (continuous spurious
    adoptions); the placer clamps, and health judges the SAME lease."""
    from hstream_tpu.placer.core import Placer

    store = open_store("mem://")
    ctx = ServerContext(store, port=1111, owns_store=False,
                        placer_interval_ms=5000,
                        heartbeat_lease_ms=1000)
    try:
        assert ctx.placer.lease_ms == 15_000
        assert ctx.heartbeat_lease_ms == 15_000
        # disarmed: no clamp — the lease is never consulted
        assert Placer(None, interval_ms=None,
                      lease_ms=1000).lease_ms == 1000
        # a sane config is left alone
        assert Placer(None, interval_ms=100,
                      lease_ms=800).lease_ms == 800
    finally:
        ctx.shutdown()
        store.close()


# ---- ownerless-query health gap (ISSUE 17 satellite 2) ----------------------


def test_dead_owner_heartbeat_lapse_reads_stalled_dead():
    from hstream_tpu.server.health import evaluate_query
    from hstream_tpu.server.persistence import QueryInfo

    store, ctx1, ctx2 = _two_contexts()
    try:
        ctx1.persistence.insert_query(QueryInfo(
            query_id="q1", sql="select", created_time_ms=BASE,
            query_type="stream", status=TaskStatus.CREATED, sink="s"))
        ctx1.persistence.set_query_status("q1", TaskStatus.RUNNING)
        scheduler.record_assignment(ctx1, "q1")
        # regression pin: owned by a LIVE peer (fresh heartbeat) ->
        # healthy from ctx2's point of view, never re-placed
        h = evaluate_query(ctx2, "q1")
        assert h["verdict"] == "OK"
        assert not scheduler.try_adopt_live(
            ctx2, "q1", lease_ms=ctx2.heartbeat_lease_ms)
        # the owner dies silently: its heartbeat lapses
        _rewrite_hb(ctx1, "q1", scheduler.now_ms() - 60_000)
        h = evaluate_query(ctx2, "q1")
        assert h["verdict"] == "STALLED"
        assert "dead" in h["reasons"]
        stalled = [e for e in ctx2.events.query(limit=100)
                   if e["kind"] == "query_stalled"]
        assert stalled and "dead" in stalled[-1]["reasons"]
    finally:
        ctx2.shutdown()
        ctx1.shutdown()
        store.close()


# ---- in-process armed clusters ----------------------------------------------


def _cluster(n=3, *, interval_ms=100, lease_ms=800, store=None):
    """N armed servers over ONE shared in-memory store: the in-process
    multi-node model (boot epochs total-ordered by the config CAS)."""
    store = store or open_store("mem://")
    nodes = []
    for _ in range(n):
        server, ctx = serve(
            "127.0.0.1", 0, store=store, owns_store=False,
            placer_interval_ms=interval_ms, heartbeat_lease_ms=lease_ms,
            snapshot_interval_ms=60, load_report_interval_ms=300)
        nodes.append((server, ctx))
    return store, nodes


def _teardown(store, nodes, dead=()):
    for i, (server, ctx) in enumerate(nodes):
        if i in dead:
            continue
        server.stop(grace=0.1)
        ctx.shutdown()
    store.close()


def _kill(server, ctx):
    """Crash a node: no drop_assignment, no record cleanup — its
    scheduler records simply stop heartbeating."""
    ctx.placer.stop()
    ctx.supervisor.shutdown()
    server.stop(grace=0)
    for task in list(ctx.running_queries.values()):
        try:
            task.stop(detach=True)
        except Exception:  # noqa: BLE001
            pass
    ctx.running_queries.clear()
    ctx.load_reporter.stop()


def _owners(nodes, qid, dead=()):
    return [i for i, (_s, c) in enumerate(nodes)
            if i not in dead and qid in c.running_queries]


def _stub(ctx):
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    return ch, HStreamApiStub(ch)


def _admin(stub, cmd, **kw):
    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command=cmd, args=rec.dict_to_struct(kw)))
    return json.loads(resp.result)


CSAS = ("CREATE STREAM {sink} AS SELECT k, COUNT(*) AS c FROM {src} "
        "GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")


def test_cluster_places_on_least_loaded_and_exposes_scores():
    store, nodes = _cluster(3)
    ch = None
    try:
        _s0, c0 = nodes[0]
        ch, stub = _stub(c0)
        stub.CreateStream(pb.Stream(stream_name="src"))
        # every node must have published a record before placement ranks
        assert _wait(lambda: len(c0.placer.scores()) == 3, timeout=10)
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk", src="src")))
        # the offer/adopt pipeline lands the query on exactly one node
        assert _wait(lambda: len(_owners(nodes, _qid(c0))) == 1,
                     timeout=15)
        qid = _qid(c0)
        st = _admin(stub, "placer")
        assert st["armed"] and len(st["nodes"]) == 3
        decision = next(d for d in st["decisions"]
                        if d["action"] == "place")
        assert decision["reason"] == "least_loaded"
        assert decision["query"] == qid
        assert set(decision["scores"]) == set(st["nodes"])
        # the winner really was ranked least-loaded at decision time
        assert decision["target"] \
            == min(sorted(decision["scores"]),
                   key=lambda n: (decision["scores"][n], n))
        rec_ = st["placements"][qid]
        assert rec_["state"] == "owned"
        # counters + gauge on the exporter (ISSUE 17 satellite 1)
        from hstream_tpu.stats.prometheus import render_metrics

        text = render_metrics(c0)
        assert "placement_decisions" in text
        assert 'placer_node_score{node="' in text
    finally:
        if ch is not None:
            ch.close()
        _teardown(store, nodes)


def _qid(ctx):
    qs = [q.query_id for q in ctx.persistence.get_queries()]
    assert len(qs) == 1
    return qs[0]


def test_full_lifecycle_place_kill_adopt_rebalance():
    """The acceptance scenario in one run: queries placed, the owner
    killed, a survivor adopts within the lease, and a later boot pulls
    load over through a rebalance offer."""
    store, nodes = _cluster(1, lease_ms=800)
    ch = None
    dead = set()
    try:
        _s0, c0 = nodes[0]
        ch, stub = _stub(c0)
        stub.CreateStream(pb.Stream(stream_name="src"))
        # two queries on the lone node: both place locally
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk1", src="src")))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk2", src="src")))
        assert _wait(lambda: len(c0.running_queries) == 2, timeout=15)

        # REBALANCE: two fresh idle peers boot; the skew (2 vs 0) must
        # move exactly one query — hysteresis keeps the other local
        for _ in range(2):
            server, ctx = serve(
                "127.0.0.1", 0, store=store, owns_store=False,
                placer_interval_ms=100, heartbeat_lease_ms=800,
                snapshot_interval_ms=60, load_report_interval_ms=300)
            nodes.append((server, ctx))
        qids = sorted(q.query_id for q in c0.persistence.get_queries())
        assert _wait(
            lambda: sorted(len(_owners(nodes, q)) for q in qids) == [1, 1]
            and len(c0.running_queries) == 1, timeout=20)
        moved = next(q for q in qids if q not in c0.running_queries)
        move = next(d for d in c0.placer.status()["decisions"]
                    if d["action"] == "rebalance")
        assert move["reason"] == "load_skew"
        assert move["query"] == moved

        # KILL the adopter: the moved query's records stop heartbeating
        owner_idx = _owners(nodes, moved)[0]
        assert owner_idx != 0
        _kill(*nodes[owner_idx])
        dead.add(owner_idx)
        t_kill = time.time()
        assert _wait(lambda: len(_owners(nodes, moved, dead)) == 1,
                     timeout=15)
        adopt_s = time.time() - t_kill
        # adoption waits out the lease, then lands within a few ticks
        assert adopt_s < 10, f"adoption took {adopt_s:.1f}s"
        # never two owners; the record names the adopter, owned
        survivors = _owners(nodes, moved, dead)
        assert len(survivors) == 1
        a = scheduler.assignment(c0, moved)
        adopter_ctx = nodes[survivors[0]][1]
        assert a["node"] == scheduler.node_name(adopter_ctx)
        assert a["state"] == "owned"
        # the adopter journaled + counted the adoption
        adopts = [d for d in adopter_ctx.placer.status()["decisions"]
                  if d["action"] == "adopt"]
        assert adopts and adopts[-1]["reason"] in ("lease_lapsed",
                                                   "offered")
    finally:
        if ch is not None:
            ch.close()
        _teardown(store, nodes, dead)


def test_restarting_owner_defers_to_live_adopter():
    """Boot-time guard (armed): a server restarting with a HIGHER boot
    epoch must not snatch back a query a live peer owns and heartbeats
    — resume_persisted skips it even though pure epoch order says
    adopt."""
    store, nodes = _cluster(1, lease_ms=5000)
    ch = None
    try:
        _s0, c0 = nodes[0]
        ch, stub = _stub(c0)
        stub.CreateStream(pb.Stream(stream_name="src"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk", src="src")))
        assert _wait(lambda: len(c0.running_queries) == 1, timeout=15)
        qid = _qid(c0)
        # a second armed server boots on the same store (higher epoch):
        # the record's heartbeat is fresh, so it must stand down
        server2, ctx2 = serve(
            "127.0.0.1", 0, store=store, owns_store=False,
            placer_interval_ms=100, heartbeat_lease_ms=5000,
            load_report_interval_ms=300)
        nodes.append((server2, ctx2))
        assert ctx2.boot_epoch > c0.boot_epoch
        assert qid not in ctx2.running_queries
        # and its sweeps keep refusing while the owner heartbeats
        time.sleep(0.6)
        assert qid not in ctx2.running_queries
        assert scheduler.assignment(ctx2, qid)["node"] \
            == scheduler.node_name(c0)
        assert qid in c0.running_queries
    finally:
        if ch is not None:
            ch.close()
        _teardown(store, nodes)


def _cas_put(ctx, key, value):
    from hstream_tpu.store.versioned import VersionMismatch

    for _ in range(64):
        cur = ctx.config.get(key)
        try:
            ctx.config.put(key, value,
                           base_version=None if cur is None else cur[0])
            return True
        except VersionMismatch:
            continue
    return False


def test_owner_self_fences_when_ownership_lost():
    """A slow-but-alive owner whose record was taken (a delayed tick
    let the lease lapse and a peer live-adopted) must STOP its local
    task — its next heartbeat sees the loss and self-fences, so there
    are never two live owners emitting results."""
    store, nodes = _cluster(1, lease_ms=800)
    ch = None
    try:
        _s0, c0 = nodes[0]
        ch, stub = _stub(c0)
        stub.CreateStream(pb.Stream(stream_name="src"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk", src="src")))
        assert _wait(lambda: len(c0.running_queries) == 1, timeout=15)
        qid = _qid(c0)
        key = "scheduler/query/" + qid
        # a "peer" steals the record — exactly what try_adopt_live
        # writes — and keeps its heartbeat FRESH while we wait, so
        # c0's sweep cannot legitimately take the query back
        thief = {"node": "server-99@x:1", "epoch": 999,
                 "state": "owned"}

        def fenced():
            # wait on the journaled decision — it lands AFTER the pop
            # and the (potentially slow) crash-style task stop
            _cas_put(c0, key, json.dumps(
                dict(thief, hb_ms=scheduler.now_ms())).encode())
            return any(d["action"] == "self_fence" and d["query"] == qid
                       for d in c0.placer.status()["decisions"])

        assert _cas_put(c0, key, json.dumps(
            dict(thief, hb_ms=scheduler.now_ms())).encode())
        assert _wait(fenced, timeout=10)
        assert qid not in c0.running_queries
        # crash-style fence: status stays RUNNING (the new owner's to
        # manage), no snapshot/status write raced the adopter, and the
        # thief's record stands untouched
        assert c0.persistence.get_query(qid).status == TaskStatus.RUNNING
        assert scheduler.assignment(c0, qid)["node"] == "server-99@x:1"
        fence = next(d for d in c0.placer.status()["decisions"]
                     if d["action"] == "self_fence")
        assert fence["reason"] == "ownership_lost"
        # the fenced loser stays fenced while the record is live
        time.sleep(0.3)
        assert qid not in c0.running_queries
    finally:
        if ch is not None:
            ch.close()
        _teardown(store, nodes)
