"""IngestPipeline + staged-batch path: must match direct process_columnar
results exactly, including the gap-guard fallback and deferred closes."""
from __future__ import annotations

import numpy as np

from hstream_tpu.engine import (
    AggKind,
    AggSpec,
    AggregateNode,
    ColumnType,
    QueryExecutor,
    Schema,
    SourceNode,
    TumblingWindow,
)
from hstream_tpu.engine.expr import Col
from hstream_tpu.engine.pipeline import IngestPipeline

BASE = 1_700_000_000_000


def make_ex(**kw):
    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("sensors", schema),
        group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "cnt"),
              AggSpec(AggKind.SUM, "total", input=Col("temp"))],
    )
    ex = QueryExecutor(node, schema, emit_changes=False, initial_keys=256,
                      batch_capacity=1024, **kw)
    for k in range(8):
        ex.key_id_for((f"d{k}",))
    return ex


def gen_batches(n_batches, batch=512, gap_at=None, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    t = BASE
    for i in range(n_batches):
        if gap_at is not None and i == gap_at:
            t += 500_000_000  # huge stream-time jump -> gap guard
        kids = rng.integers(0, 8, size=batch).astype(np.int32)
        temps = (np.rint(rng.normal(20, 5, batch) * 10)
                 .astype(np.float32) * np.float32(0.1))
        ts = t + np.arange(batch, dtype=np.int64) * 4
        t += batch * 4
        out.append((kids, ts, {"temp": temps}))
    return out


def canon(rows):
    return sorted((r["device"], r["winStart"], r["cnt"], round(r["total"], 2))
                  for r in rows)


def run_direct(batches):
    ex = make_ex()
    rows = []
    for kids, ts, cols in batches:
        rows.extend(ex.process_columnar(kids, ts, cols))
    return ex, rows


def run_pipelined(batches, depth=3, workers=1, **kw):
    ex = make_ex()
    for k, v in kw.items():
        setattr(ex, k, v)
    pipe = IngestPipeline(ex, depth=depth, workers=workers)
    rows = []
    for kids, ts, cols in batches:
        rows.extend(pipe.submit(kids, ts, cols))
    rows.extend(pipe.flush())
    pipe.close()
    return ex, rows


def test_pipeline_matches_direct():
    batches = gen_batches(30)
    _, direct = run_direct(batches)
    _, piped = run_pipelined(batches)
    assert len(direct) > 0
    assert canon(direct) == canon(piped)


def test_pipeline_multiworker_matches_direct_exactly():
    """Worker POOL (out-of-order encode) + reorder ring: emitted rows
    must be IDENTICAL to the synchronous path, ordering included."""
    batches = gen_batches(40)
    _, direct = run_direct(batches)
    _, piped = run_pipelined(batches, depth=4, workers=4)
    assert len(direct) > 0
    assert direct == piped  # byte-identical rows, order preserved


def test_pipeline_multiworker_gap_fallback():
    batches = gen_batches(24, gap_at=12)
    _, direct = run_direct(batches)
    _, piped = run_pipelined(batches, depth=4, workers=3)
    assert canon(direct) == canon(piped)


def make_changes_ex():
    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("sensors", schema),
        group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "cnt"),
              AggSpec(AggKind.SUM, "total", input=Col("temp"))],
    )
    ex = QueryExecutor(node, schema, emit_changes=True, initial_keys=256,
                       batch_capacity=1024)
    for k in range(8):
        ex.key_id_for((f"d{k}",))
    return ex


def test_pipeline_async_change_drain_matches_direct_exactly():
    """Deferred + ASYNC change drain through a multi-worker pipeline:
    the full change-row sequence (after the flush barrier) must equal
    the synchronous inline-decode path exactly — same rows, same
    order."""
    batches = gen_batches(30)
    ex_d = make_changes_ex()
    direct = []
    for kids, ts, cols in batches:
        direct.extend(ex_d.process_columnar(kids, ts, cols))

    ex_p = make_changes_ex()
    ex_p.defer_change_decode = True
    ex_p.change_drain_depth = 3
    ex_p.async_change_drain = True
    pipe = IngestPipeline(ex_p, depth=4, workers=2)
    piped = []
    for kids, ts, cols in batches:
        piped.extend(pipe.submit(kids, ts, cols))
    piped.extend(pipe.flush())
    piped.extend(ex_p.flush_changes())
    pipe.close()
    assert not ex_p.has_pending_changes()
    assert len(direct) > 0
    assert direct == piped


def test_pipeline_stage_stats():
    batches = gen_batches(10)
    ex = make_ex()
    pipe = IngestPipeline(ex, depth=3, workers=2)
    for kids, ts, cols in batches:
        pipe.submit(kids, ts, cols)
    pipe.flush()
    stats = pipe.stats()
    pipe.close()
    for key in ("encode_s", "step_s", "upload_wait_s", "drain_s",
                "wall_s", "encode_occupancy", "step_occupancy"):
        assert key in stats
    assert stats["encode_s"] > 0
    assert stats["step_s"] > 0
    assert 0.0 <= stats["encode_occupancy"] <= 1.0
    pipe.reset_stats()  # must not raise after close


def test_pipeline_gap_fallback_matches_direct():
    batches = gen_batches(20, gap_at=10)
    _, direct = run_direct(batches)
    _, piped = run_pipelined(batches)
    assert canon(direct) == canon(piped)


def test_pipeline_deferred_close_decode():
    batches = gen_batches(30)
    _, direct = run_direct(batches)
    ex, piped = run_pipelined(batches, defer_close_decode=True)
    assert piped == []  # closes deferred, nothing decoded inline
    deferred = ex.drain_closed()
    assert canon(direct) == canon(deferred)


def test_pipeline_epoch_rebase_fallback():
    ex = make_ex()
    ex.rebase_threshold = 1 << 22  # force rebases every ~4194s of stream
    batches = gen_batches(12)
    # stretch stream time so multiple rebases occur across the run
    stretched = [(k, BASE + (t - BASE) * 900, c) for k, t, c in batches]
    direct_rows = []
    ex2 = make_ex()
    ex2.rebase_threshold = 1 << 22
    for kids, ts, cols in stretched:
        direct_rows.extend(ex2.process_columnar(kids, ts, cols))
    pipe = IngestPipeline(ex, depth=3)
    rows = []
    for kids, ts, cols in stretched:
        rows.extend(pipe.submit(kids, ts, cols))
    rows.extend(pipe.flush())
    pipe.close()
    assert canon(direct_rows) == canon(rows)


def test_pipeline_worker_error_surfaces():
    ex = make_ex()
    pipe = IngestPipeline(ex, depth=2)
    kids = np.zeros(4, np.int32)
    ts = np.full(4, BASE, np.int64)
    # missing column -> encoder thread raises; error must surface, and
    # later calls must fail fast instead of hanging
    pipe.submit(kids, ts, {})
    import pytest as _pytest
    with _pytest.raises((KeyError, RuntimeError)):
        pipe.flush()
    with _pytest.raises(RuntimeError):
        pipe.flush()
    with _pytest.raises(RuntimeError):
        pipe.submit(kids, ts, {"temp": np.zeros(4, np.float32)})


def test_sharded_executor_with_pipeline():
    import jax
    from hstream_tpu.parallel import ShardedQueryExecutor
    from hstream_tpu.engine import (AggKind, AggSpec, AggregateNode,
                                    ColumnType, Schema, SourceNode,
                                    TumblingWindow)
    from hstream_tpu.engine.expr import Col

    devs = jax.devices()
    if len(devs) < 2:
        import pytest as _pytest
        _pytest.skip("needs multi-device mesh")
    import numpy as _np
    from jax.sharding import Mesh

    mesh = Mesh(_np.array(devs[:2]).reshape(2, 1), ("data", "key"))
    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("sensors", schema), group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "cnt"),
              AggSpec(AggKind.SUM, "total", input=Col("temp"))])
    ex = ShardedQueryExecutor(node, schema, mesh=mesh, emit_changes=False,
                              initial_keys=256, batch_capacity=1024)
    for k in range(8):
        ex.key_id_for((f"d{k}",))
    batches = gen_batches(12)
    pipe = IngestPipeline(ex, depth=2)
    rows = []
    for kids, ts, cols in batches:
        rows.extend(pipe.submit(kids, ts, cols))
    rows.extend(pipe.flush())
    pipe.close()
    _, direct = run_direct(batches)
    assert canon(direct) == canon(rows)
