"""Admin store-ops verbs (SendAdminCommand), LDQuery-lite virtual
tables, mesh-exclusion visibility, and k8s manifest sanity."""
from __future__ import annotations

import glob
import json
import os
import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

BASE = 1_700_000_000_000
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def server_stub():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    yield stub, ctx
    channel.close()
    server.stop(grace=1)
    ctx.shutdown()


def admin(stub, command, **kwargs):
    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command=command, args=rec.dict_to_struct(kwargs)))
    return json.loads(resp.result)


def append_rows(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    return stub.Append(req)


def test_offsets_trim_findtime(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="ops"))
    for i in range(5):
        append_rows(stub, "ops", [{"i": i}], [BASE + i * 1000])
    off = admin(stub, "offsets", stream="ops")
    assert off["tail_lsn"] == 5 and off["trim_point"] == 0
    # find_time operates on APPEND time (store wall clock)
    ft = admin(stub, "find-time", stream="ops", ts_ms=BASE)
    assert ft["lsn"] == 1      # everything appended after BASE (2023)
    far = admin(stub, "find-time", stream="ops",
                ts_ms=int(time.time() * 1000) + 3_600_000)
    assert far["lsn"] == 6     # tail+1: nothing that late
    tr = admin(stub, "trim", stream="ops", lsn=2)
    assert tr["trim_point"] == 2
    off = admin(stub, "offsets", stream="ops")
    assert off["trim_point"] == 2 and off["tail_lsn"] == 5


def test_sub_lag(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="lagged"))
    append_rows(stub, "lagged", [{"i": i} for i in range(4)],
                [BASE + i for i in range(4)])
    stub.CreateSubscription(pb.Subscription(
        subscription_id="lagsub", stream_name="lagged"))
    lag = admin(stub, "sub-lag", subscription="lagsub")
    assert lag["tail_lsn"] == 1    # one appended batch = one LSN
    assert lag["lag"] == 1 - lag["committed_lsn"]
    got = stub.Fetch(pb.FetchRequest(subscription_id="lagsub",
                                     timeout_ms=1000, max_size=10))
    stub.Acknowledge(pb.AcknowledgeRequest(
        subscription_id="lagsub",
        ack_ids=[rr.record_id for rr in got.received_records]))
    deadline = time.time() + 10
    while time.time() < deadline:
        lag = admin(stub, "sub-lag", subscription="lagsub")
        if lag["lag"] == 0:
            break
        time.sleep(0.1)
    assert lag["lag"] == 0


def test_snapshots_and_replicas_and_assignments(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="snapsrc"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM snapsrc GROUP BY k, "
                   "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"))
    append_rows(stub, "snapsrc", [{"k": "a"}], [BASE])
    # force a snapshot via terminate (graceful stop persists state)
    stub.TerminateQueries(pb.TerminateQueriesRequest(query_ids=[q.id]))
    snaps = admin(stub, "snapshots")
    assert q.id in snaps and snaps[q.id]["bytes"] > 0
    reps = admin(stub, "replicas")
    assert reps["role"] == "single"
    # assignments: the terminated query's record is dropped
    assert q.id not in admin(stub, "assignments")


def test_admin_cli_quota_and_flow_verbs(server_stub, capsys):
    """The operator CLI's new flow-control verbs end to end:
    quota set/get/list/unset and the live flow status table."""
    from hstream_tpu.admin import main as admin_main

    _, ctx = server_stub
    argv = ["--port", str(ctx.port)]
    assert admin_main(argv + ["quota", "set", "stream/cliq",
                              "--records", "7",
                              "--bytes", "4096"]) == 0
    out = capsys.readouterr().out
    assert "stream/cliq" in out and "7" in out
    assert admin_main(argv + ["quota", "get", "stream/cliq"]) == 0
    assert "4096" in capsys.readouterr().out
    assert admin_main(argv + ["quota", "list"]) == 0
    assert "stream/cliq" in capsys.readouterr().out
    assert admin_main(argv + ["flow"]) == 0
    out = capsys.readouterr().out
    assert "level" in out and "signal" in out and "quota" in out
    assert admin_main(argv + ["quota", "unset", "stream/cliq"]) == 0
    capsys.readouterr()
    assert admin_main(argv + ["quota", "get", "stream/cliq"]) == 0
    assert "unset" in capsys.readouterr().out


def test_virtual_tables(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="vt1", replication_factor=2))
    stub.CreateStream(pb.Stream(stream_name="vt2"))
    append_rows(stub, "vt1", [{"x": 1}], [BASE])
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="SELECT name, tail_lsn FROM __streams__ "
                  "WHERE replication_factor > 1;"))
    rows = [rec.struct_to_dict(r) for r in out.result_set]
    assert {r["name"] for r in rows} == {"vt1"}
    assert rows[0]["tail_lsn"] == 1
    assert "replication_factor" not in rows[0]  # projection applied
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="SELECT * FROM __queries__;"))
    assert isinstance(out.result_set, object)
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="SELECT * FROM __stats__;"))
    rows = [rec.struct_to_dict(r) for r in out.result_set]
    assert any(r.get("stream") == "vt1" for r in rows)


def test_virtual_table_names_are_reserved(server_stub):
    """CREATE STREAM/VIEW colliding with a virtual table is rejected
    (a user view named __streams__ would be unreachable); a user view
    that ALREADY exists under a reserved name (pre-guard state) keeps
    winning the SELECT route (ISSUE 1 satellite)."""
    from hstream_tpu.server.views import Materialization

    stub, ctx = server_stub
    with pytest.raises(grpc.RpcError) as e:
        stub.CreateStream(pb.Stream(stream_name="__streams__"))
    assert e.value.code() == grpc.StatusCode.INTERNAL
    assert "reserved" in e.value.details()
    with pytest.raises(grpc.RpcError) as e:
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE STREAM __queries__ AS SELECT x FROM vt1;"))
    assert "reserved" in e.value.details()
    with pytest.raises(grpc.RpcError) as e:
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW __views__ AS SELECT x, COUNT(*) AS c "
                      "FROM vt1 GROUP BY x, "
                      "TUMBLING (INTERVAL 10 SECOND);"))
    assert "reserved" in e.value.details()
    assert "__views__" not in ctx.views.names()
    # CreateQuery's user-supplied id becomes the sink STREAM name
    with pytest.raises(grpc.RpcError) as e:
        stub.CreateQuery(pb.CreateQueryRequest(
            id="__streams__",
            query_text="SELECT x, COUNT(*) AS c FROM vt1 GROUP BY x, "
                       "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"))
    assert "reserved" in e.value.details()
    # pre-existing user view under a reserved name: SELECT routes to IT,
    # not to the virtual table
    mat = Materialization(group_cols=["g"])
    mat.add_closed([{"g": "legacy", "c": 7}])
    ctx.views.register("__stats__", mat)
    try:
        out = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM __stats__;"))
        rows = [rec.struct_to_dict(r) for r in out.result_set]
        assert rows == [{"g": "legacy", "c": 7}]
    finally:
        ctx.views.remove("__stats__")
    # with the view gone the virtual table answers again
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="SELECT * FROM __stats__;"))
    rows = [rec.struct_to_dict(r) for r in out.result_set]
    assert any(r.get("stream") == "vt1" for r in rows)


def test_explain_notes_mesh_exclusion(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="l1"))
    stub.CreateStream(pb.Stream(stream_name="r1"))
    # interval (stream-stream) joins shard since ISSUE 16 — the mesh
    # line must name the shardable topology, not an exclusion
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="EXPLAIN SELECT l1.k, COUNT(*) AS c FROM l1 "
                  "INNER JOIN r1 WITHIN (INTERVAL 1 SECOND) "
                  "ON l1.k = r1.k GROUP BY l1.k, "
                  "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"))
    text = rec.struct_to_dict(out.result_set[0])["explain"]
    assert "MESH: shardable" in text and "JOIN" in text
    # TOPK planes have no elementwise shard merge — still excluded
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="EXPLAIN SELECT k, TOPK(v, 3) AS t FROM l1 "
                  "GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
                  "EMIT CHANGES;"))
    text = rec.struct_to_dict(out.result_set[0])["explain"]
    assert "MESH: single-chip" in text and "TOPK" in text
    out = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="EXPLAIN SELECT k, COUNT(*) AS c FROM l1 GROUP BY k, "
                  "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"))
    text = rec.struct_to_dict(out.result_set[0])["explain"]
    assert "MESH: shardable" in text


def test_k8s_manifests_parse_and_reference_real_entrypoints():
    import yaml

    files = glob.glob(os.path.join(REPO, "k8s", "*.yaml"))
    assert len(files) >= 4
    cmds = []
    for f in files:
        for doc in yaml.safe_load_all(open(f)):
            assert doc and "kind" in doc, f
            tmpl = (doc.get("spec", {}).get("template", {})
                    .get("spec", {}).get("containers", []))
            for c in tmpl:
                cmds.append((c.get("command", []), c.get("args", [])))
    mods = [cmd[2] for cmd, _ in cmds if len(cmd) >= 3 and cmd[1] == "-m"]
    assert "hstream_tpu.server.main" in mods
    assert "hstream_tpu.store.replica" in mods


def test_append_compression_knob():
    """--append-compression zlib round-trips through the store (the
    reference server.hs --compression flag)."""
    from hstream_tpu.server.main import serve as _serve

    server, ctx = _serve("127.0.0.1", 0, "mem://",
                         append_compression="zlib")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="z"))
        append_rows(stub, "z", [{"v": "x" * 500}] * 8,
                    [BASE + i for i in range(8)])
        stub.CreateSubscription(pb.Subscription(
            subscription_id="zs", stream_name="z"))
        got = stub.Fetch(pb.FetchRequest(subscription_id="zs",
                                         timeout_ms=2000, max_size=20))
        rows = [rec.record_to_dict(rec.parse_record(r.record))
                for r in got.received_records]
        assert len(rows) == 8 and all(r["v"] == "x" * 500 for r in rows)
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_admin_promote_verb_and_replicas_leader_status():
    """ISSUE 9 operator surface: `admin replicas` reports the leader's
    epoch/fencing/dedup state, `admin promote target=` runs the
    planned handoff (promote + self-fence + seal), the promotions
    counter ticks, and the fenced server refuses further appends with
    the NOT_LEADER hint."""
    import socket

    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import serve_follower

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    fport = s.getsockname()[1]
    s.close()
    f_store = open_store("mem://")
    fsrv, svc = serve_follower(f_store, f"127.0.0.1:{fport}",
                               node_id="adm-f")
    server, ctx = serve("127.0.0.1", 0, "mem://",
                        replicate=f"127.0.0.1:{fport}",
                        replication_factor=2,
                        replica_ack_timeout_ms=2500)
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    try:
        stub.CreateStream(pb.Stream(stream_name="adm"))
        append_rows(stub, "adm", [{"i": 1}], [BASE])

        out = admin(stub, "replicas")
        assert out["role"] == "leader"
        lead = out["leader"]
        assert lead["epoch"] == 0 and lead["fenced"] is False
        assert lead["ack_timeout_ms"] == 2500  # the threaded flag
        assert lead["dedup_window"] == 0

        res = admin(stub, "promote", target=f"127.0.0.1:{fport}",
                    leader_addr="next:1")
        assert res["ok"] and res["epoch"] == 1
        assert res["node_id"] == "adm-f"
        assert svc.is_leader and svc.epoch == 1
        assert ctx.stats.stream_stat_get("promotions", "_store") == 1

        out = admin(stub, "replicas")
        assert out["leader"]["fenced"] is True
        assert out["leader"]["fenced_by_epoch"] == 1
        assert out["leader"]["leader_hint"] == "next:1"

        try:
            append_rows(stub, "adm", [{"i": 2}], [BASE + 1])
            raise AssertionError("fenced server accepted an append")
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.UNAVAILABLE
            assert "not_leader leader_hint=next:1" in e.details()

        # CLI shaping: the leader-status row leads, sorted keys
        from hstream_tpu.admin import cmd_promote, cmd_replicas

        rows = cmd_replicas(stub, None)
        assert rows[0]["role"] == "leader-status"
        assert rows[0]["fenced"] is True

        class _Args:
            target = None
            replicas = f"127.0.0.1:{fport}"
            leader_addr = None

        res2 = cmd_promote(stub, _Args)[0]
        # leader-death path through the CLI: re-promoting the already
        # promoted follower raises its epoch again
        assert res2["ok"] and res2["epoch"] == 2

        # promote with neither form is a loud usage error
        try:
            admin(stub, "promote")
            raise AssertionError("argless promote accepted")
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.INTERNAL
    finally:
        channel.close()
        server.stop(grace=1)
        try:
            ctx.shutdown()
        except Exception:  # noqa: BLE001 — fenced store refuses final
            pass           # status writes
        svc.close()
        fsrv.stop(grace=1)


def test_admin_locks_verb_arm_ledger_disarm(server_stub, capsys):
    """ISSUE 14: the `admin locks` verb — arm the witness at runtime,
    exercise instrumented subsystems, read the ledger (named locks,
    acquire/contention counts, wait/hold percentiles, order graph,
    cycle reports), then disarm and see a clean slate."""
    from hstream_tpu.admin import main as admin_main
    from hstream_tpu.common.locktrace import LOCKTRACE

    stub, ctx = server_stub
    LOCKTRACE.disarm()
    argv = ["--port", str(ctx.port)]
    try:
        out = admin(stub, "locks", action="arm")
        assert out["armed"] is True
        # drive instrumented paths: context.running + supervisor
        admin(stub, "supervisor")
        stub.ListQueries(pb.ListQueriesRequest())
        out = admin(stub, "locks")
        assert out["armed"] is True and out["cycles"] == []
        assert out["locks"], "armed ledger should have entries"
        some = next(iter(out["locks"].values()))
        assert "acquires" in some and "contentions" in some
        assert "wait_p50_ms" in some and "hold_p99_ms" in some
        # CLI rendering
        assert admin_main(argv + ["locks"]) == 0
        text = capsys.readouterr().out
        assert "(witness)" in text and "armed" in text
        out = admin(stub, "locks", action="disarm")
        assert out["armed"] is False and out["locks"] == {}
        # unknown action refused loudly
        with pytest.raises(grpc.RpcError):
            admin(stub, "locks", action="explode")
    finally:
        LOCKTRACE.disarm()
