"""Regression tests pinned to reproduced bugs (round-3 ADVICE/VERDICT):

(a) materialized-view row keys built from string-typed values only, so
    distinct numeric group keys collided on (winStart, ()) and silently
    overwrote each other (data loss in pull queries);
(b) subscription dispatch dropped a fetched batch on a full consumer
    queue AFTER it was noted in the AckWindow — never redelivered while
    the server runs, ack lower bound stalled;
(c) executor.peek() called from gRPC threads while the query task
    mutates state concurrently (unsynchronized _open/state access).

(d) — read checkpoints committed before windows close — is covered by
the operator-state checkpoint/resume tests in test_checkpoint_resume.py.
"""

import queue
import threading
import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached
from hstream_tpu.server.views import Materialization

BASE = 1_700_000_000_000


@pytest.fixture()
def server_stub():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    yield stub, ctx
    channel.close()
    server.stop(grace=1)
    ctx.shutdown()


def append_rows(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    return stub.Append(req)


# ---- (a) numeric group keys must not collide in view row keys ---------------


def test_view_rowkey_distinct_numeric_groups():
    mat = Materialization(group_cols=["k"])
    mat.add_closed([
        {"k": 1, "c": 5, "winStart": BASE, "winEnd": BASE + 10},
        {"k": 2, "c": 7, "winStart": BASE, "winEnd": BASE + 10},
    ])
    rows = mat.snapshot()
    assert len(rows) == 2, "distinct numeric group keys must both survive"
    assert {r["k"] for r in rows} == {1, 2}


def test_view_rowkey_updates_same_group():
    mat = Materialization(group_cols=["k"])
    mat.add_closed([{"k": 1, "c": 5, "winStart": BASE}])
    mat.add_closed([{"k": 1, "c": 9, "winStart": BASE}])
    rows = mat.snapshot()
    assert len(rows) == 1 and rows[0]["c"] == 9


def test_view_rowkey_stateless_keeps_every_row():
    mat = Materialization(group_cols=None)
    mat.add_closed([{"a": 1}, {"a": 1}])  # identical rows, no group identity
    assert len(mat.snapshot()) == 2


def test_view_pull_query_numeric_group_key(server_stub):
    """End-to-end: a view grouped on a numeric column serves every
    distinct key (pre-fix: all numeric keys collapsed to one row)."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="numsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW numview AS SELECT sensor, COUNT(*) AS c "
                  "FROM numsrc GROUP BY sensor, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-numview")
    append_rows(stub, "numsrc",
                [{"sensor": 1, "v": 1.0}, {"sensor": 2, "v": 2.0},
                 {"sensor": 2, "v": 3.0}],
                [BASE, BASE + 1, BASE + 2])
    # window-closer
    append_rows(stub, "numsrc", [{"sensor": 9, "v": 0.0}], [BASE + 30_000])
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM numview;"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        closed = [r for r in rows if r.get("winStart") == BASE]
        if len({r.get("sensor") for r in closed}) >= 2:
            break
        time.sleep(0.2)
    closed = [r for r in rows if r.get("winStart") == BASE]
    sensors = {r.get("sensor") for r in closed}
    assert {1, 2} <= sensors, rows
    by_sensor = {r["sensor"]: r["c"] for r in closed}
    assert by_sensor[1] == 1 and by_sensor[2] == 2


def test_emitted_group_cols_resolves_aliases():
    """Aliased group keys emit under the alias: the view row key must use
    the emitted name, not the plan column name (else every group's
    row.get('city') is None and all groups collapse again)."""
    from hstream_tpu.sql.codegen import emitted_group_cols, stream_codegen

    plan = stream_codegen(
        "SELECT city AS c, COUNT(*) AS n FROM s GROUP BY city, "
        "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    assert emitted_group_cols(plan.node) == ["c"]
    plain = stream_codegen(
        "SELECT city, COUNT(*) FROM s GROUP BY city, "
        "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    assert emitted_group_cols(plain.node) == ["city"]


def test_view_pull_query_aliased_group_key(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="aliassrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW aliasview AS SELECT city AS c, "
                  "COUNT(*) AS n FROM aliassrc GROUP BY city, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-aliasview")
    append_rows(stub, "aliassrc",
                [{"city": "sf"}, {"city": "la"}, {"city": "la"}],
                [BASE, BASE + 1, BASE + 2])
    append_rows(stub, "aliassrc", [{"city": "zz"}], [BASE + 30_000])
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM aliasview;"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        closed = [r for r in rows if r.get("winStart") == BASE]
        if len({r.get("c") for r in closed}) >= 2:
            break
        time.sleep(0.2)
    closed = {r["c"]: r["n"] for r in rows if r.get("winStart") == BASE}
    assert closed.get("sf") == 1 and closed.get("la") == 2, rows


# ---- (b) dispatch must never drop a noted batch -----------------------------


def _wait(cond, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_dispatch_reoffers_when_consumer_queue_full(server_stub,
                                                    monkeypatch):
    """A batch that finds the consumer queue full is re-offered, not
    dropped: every appended record is eventually delivered."""
    import hstream_tpu.server.subscriptions as subs

    orig_init = subs.Consumer.__init__

    def tiny_init(self, name, credit_window=0):
        orig_init(self, name, credit_window)
        self.queue = queue.Queue(maxsize=1)  # force queue-full quickly

    monkeypatch.setattr(subs.Consumer, "__init__", tiny_init)

    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="slowsub"))
    off = pb.SubscriptionOffset(special_offset=0)  # EARLIEST
    stub.CreateSubscription(pb.Subscription(
        subscription_id="slow1", stream_name="slowsub", offset=off))
    rt = ctx.subscriptions.get("slow1")

    append_rows(stub, "slowsub", [{"n": 0}], [BASE])
    consumer = rt.register_consumer("c0")
    # wave 1 lands in the 1-slot queue; don't consume it yet
    assert _wait(lambda: not consumer.queue.empty())
    # wave 2: the dispatcher fetches + notes it, finds the queue full,
    # and must keep re-offering instead of dropping
    append_rows(stub, "slowsub", [{"n": 1}], [BASE + 1])
    time.sleep(0.6)  # several put timeouts elapse while the queue is full

    got = []
    deadline = time.time() + 10
    while time.time() < deadline and len(got) < 2:
        try:
            batch = consumer.queue.get(timeout=0.5)
        except queue.Empty:
            continue
        for rid, payload in batch:
            got.append((rid,
                        rec.record_to_dict(rec.parse_record(payload))["n"]))
    assert sorted(n for _, n in got) == [0, 1], got

    # ack everything: the lower bound must advance (no stall)
    rt.ack([rid for rid, _ in got])
    tail = ctx.store.tail_lsn(rt.logid)
    assert rt.committed_lsn >= tail - 1


def test_dead_consumer_batches_are_redelivered(server_stub):
    """Batches sitting in a dead consumer's queue are reclaimed and
    redelivered to the next consumer (pre-fix: lost until restart)."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="dcsub"))
    off = pb.SubscriptionOffset(special_offset=0)
    stub.CreateSubscription(pb.Subscription(
        subscription_id="dc1", stream_name="dcsub", offset=off))
    rt = ctx.subscriptions.get("dc1")

    append_rows(stub, "dcsub", [{"n": 1}, {"n": 2}], [BASE, BASE + 1])
    c1 = rt.register_consumer("c1")
    assert _wait(lambda: not c1.queue.empty())
    rt.unregister_consumer(c1)  # dies with undelivered batches queued

    c2 = rt.register_consumer("c2")
    got = []
    deadline = time.time() + 10
    while time.time() < deadline and len(got) < 2:
        try:
            batch = c2.queue.get(timeout=0.5)
        except queue.Empty:
            continue
        for rid, payload in batch:
            got.append(rec.record_to_dict(rec.parse_record(payload))["n"])
    assert sorted(got) == [1, 2]


# ---- (c) pull queries racing the query task ---------------------------------


def test_view_peek_concurrent_with_ingest(server_stub):
    """Hammer pull queries while the query task is mid-aggregation; no
    request may fail (pre-fix: unlocked iteration over mutating state)."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="racesrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW raceview AS SELECT city, COUNT(*) AS c "
                  "FROM racesrc GROUP BY city, "
                  "TUMBLING (INTERVAL 1 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-raceview")
    errors: list[BaseException] = []
    stop = threading.Event()

    def producer():
        t = BASE
        i = 0
        while not stop.is_set():
            try:
                append_rows(stub, "racesrc",
                            [{"city": f"c{i % 7}", "v": 1.0}
                             for _ in range(16)],
                            [t + j for j in range(16)])
            except grpc.RpcError as e:  # noqa: PERF203
                errors.append(e)
                return
            t += 1500  # advance past window close every other batch
            i += 1

    def puller():
        while not stop.is_set():
            try:
                stub.ExecuteQuery(pb.CommandQuery(
                    stmt_text="SELECT * FROM raceview;"))
            except grpc.RpcError as e:  # noqa: PERF203
                errors.append(e)
                return

    threads = [threading.Thread(target=producer, daemon=True)] + \
        [threading.Thread(target=puller, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(10)
    assert not errors, [str(e) for e in errors]


# ---- (f) sink columnar records must reach subscribers as JSON rows ----------


def test_subscription_expands_packed_columnar(server_stub):
    """A columnar-packed record (what stream_sink emits for >=32-row
    batches) must be delivered to Fetch consumers as individual JSON
    records, not one opaque RAW blob."""
    import numpy as np

    from hstream_tpu.common import columnar

    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="packed"))
    rows = [{"k": f"x{i}", "c": i} for i in range(40)]
    payload = columnar.rows_to_payload(rows, BASE)
    assert payload is not None
    req = pb.AppendRequest(stream_name="packed")
    req.records.append(rec.build_record(payload, publish_time_ms=BASE))
    stub.Append(req)
    stub.CreateSubscription(pb.Subscription(
        subscription_id="sub-packed", stream_name="packed"))
    got = stub.Fetch(pb.FetchRequest(
        subscription_id="sub-packed", timeout_ms=2000, max_size=100))
    recs = got.received_records
    assert len(recs) == 40, len(recs)
    seen = []
    for rr in recs:
        r = rec.parse_record(rr.record)
        assert r.header.flag == rec.pb.RECORD_FLAG_JSON
        seen.append(rec.record_to_dict(r))
    assert seen == rows
    # ack indices over the expanded space commit cleanly
    stub.Acknowledge(pb.AcknowledgeRequest(
        subscription_id="sub-packed",
        ack_ids=[rr.record_id for rr in recs]))


# ---- (g) batch decode row shape matches per-record decode -------------------


def test_to_rows_drop_null_matches_per_record_shape():
    import numpy as np

    from hstream_tpu.common import columnar

    ts = np.array([BASE, BASE + 1], np.int64)
    cols = {"a": ("f64", np.array([1.0, 0.0]), None),
            "b": ("f64", np.array([0.0, 2.0]), None)}
    nulls = {"a": np.array([False, True]),
             "b": np.array([True, False])}
    rows = columnar.to_rows(ts, cols, nulls, drop_null=True)
    assert rows == [{"a": 1}, {"b": 2}]
    # default keeps explicit Nones (sink/gateway consumers)
    rows = columnar.to_rows(ts, cols, nulls)
    assert rows == [{"a": 1, "b": None}, {"a": None, "b": 2}]


# ---- (h) bool group keys: only present values registered --------------------


def test_bool_group_key_no_phantom_ids():
    import numpy as np

    from hstream_tpu.engine import (
        AggKind, AggSpec, AggregateNode, ColumnType, QueryExecutor,
        Schema, SourceNode, TumblingWindow)
    from hstream_tpu.engine.expr import Col
    from hstream_tpu.server.tasks import _columnar_key_ids

    schema = Schema.of(flag=ColumnType.BOOL, v=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("flag")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c")])
    ex = QueryExecutor(node, schema, emit_changes=False,
                       initial_keys=4, batch_capacity=64)
    cols = {"flag": ("bool", np.ones(8, np.bool_), None)}
    kids = _columnar_key_ids(ex, cols, 8)
    assert len(set(kids.tolist())) == 1
    assert len(ex._key_rev) == 1  # no phantom False key registered


def test_to_rows_empty_payload_records_preserved():
    import numpy as np

    from hstream_tpu.common import columnar

    ts = np.array([BASE, BASE + 1, BASE + 2], np.int64)
    assert columnar.to_rows(ts, {}, {}) == [{}, {}, {}]


def test_empty_columnar_record_delivered_verbatim(server_stub):
    """A zero-row columnar record must NOT expand to an empty batch
    (which would park the ack window forever) — it is delivered as the
    one opaque record it is, and the checkpoint still advances."""
    import numpy as np

    from hstream_tpu.common import columnar

    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="edgy"))
    empty = columnar.encode_columnar(np.empty(0, np.int64), {})
    req = pb.AppendRequest(stream_name="edgy")
    req.records.append(rec.build_record(empty, publish_time_ms=BASE))
    req.records.append(rec.build_record({"k": "a"}, publish_time_ms=BASE))
    stub.Append(req)
    stub.CreateSubscription(pb.Subscription(
        subscription_id="sub-edgy", stream_name="edgy"))
    got = stub.Fetch(pb.FetchRequest(
        subscription_id="sub-edgy", timeout_ms=2000, max_size=10))
    assert len(got.received_records) == 2
    stub.Acknowledge(pb.AcknowledgeRequest(
        subscription_id="sub-edgy",
        ack_ids=[rr.record_id for rr in got.received_records]))
    rt = ctx.subscriptions.get("sub-edgy")
    assert rt.committed_lsn > 0  # ack window advanced


# ---- ISSUE 4: defects found by hstream-analyze ------------------------------


class _TrackingLock:
    """Duck-typed lock/condition wrapper counting acquisitions, so a
    test can pin 'this read holds the lock' without relying on a race
    the GIL usually masks."""

    def __init__(self, inner):
        self._inner = inner
        self.entered = 0

    def __enter__(self):
        self.entered += 1
        return self._inner.__enter__()

    def __exit__(self, *exc):
        return self._inner.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_subscription_shutdown_joins_dispatcher(server_stub):
    """resource-leak fix: remove() must reap the dispatcher thread —
    pre-fix it was only signalled, so DeleteSubscription could return
    while the loop was still mid-fetch against deleted state."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="reaped"))
    stub.CreateSubscription(pb.Subscription(
        subscription_id="reap1", stream_name="reaped",
        offset=pb.SubscriptionOffset(special_offset=0)))
    rt = ctx.subscriptions.get("reap1")
    rt.register_consumer("c0")
    assert _wait(lambda: rt._dispatcher is not None
                 and rt._dispatcher.is_alive())
    dispatcher = rt._dispatcher
    ctx.subscriptions.remove("reap1")  # -> rt.shutdown()
    assert not dispatcher.is_alive(), \
        "shutdown() returned with the dispatcher still running"


def test_subscription_committed_lsn_reads_under_lock(server_stub):
    """lock-guard fix: committed_lsn is written under rt.lock by the
    fetch/ack paths; the observability read must hold it too."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="lockedsub"))
    stub.CreateSubscription(pb.Subscription(
        subscription_id="ls1", stream_name="lockedsub",
        offset=pb.SubscriptionOffset(special_offset=0)))
    rt = ctx.subscriptions.get("ls1")
    rt.lock = _TrackingLock(rt.lock)
    before = rt.lock.entered
    assert rt.committed_lsn == 0
    assert rt.lock.entered == before + 1


def test_replica_oplog_seq_reads_under_cond():
    """lock-guard fix: ReplicatedStore._seq is written under _cond by
    appender threads; follower_status/oplog_seq must read it locked."""
    from hstream_tpu.store.memstore import MemLogStore
    from hstream_tpu.store.replica import ReplicatedStore

    store = ReplicatedStore(MemLogStore(), [], replication_factor=1)
    try:
        store.create_log(42)
        store.append_batch(42, [b"x"])
        store._cond = _TrackingLock(store._cond)
        before = store._cond.entered
        seq = store.oplog_seq
        assert seq >= 2  # create + append both logged
        assert store._cond.entered == before + 1
    finally:
        store.close()


def test_credit_available_reads_under_cv():
    """lock-guard fix: CreditWindow._avail is mutated under _cv by the
    dispatcher and ack threads; the gauge read must hold it."""
    from hstream_tpu.flow import CreditWindow

    cw = CreditWindow(8)
    assert cw.take_up_to(3) == 3
    cw._cv = _TrackingLock(cw._cv)
    before = cw._cv.entered
    assert cw.available == 5
    assert cw._cv.entered == before + 1


def test_store_dir_bytes_walk_is_ttl_bounded(tmp_path, monkeypatch):
    """blocking-hot fix: the scrape-path store-footprint walk runs at
    most once per TTL — pre-fix every /metrics hit walked the whole
    store directory tree."""
    import os as _os

    from hstream_tpu.stats import prometheus as prom

    (tmp_path / "seg1.dat").write_bytes(b"x" * 10)
    (tmp_path / "wal.log").write_bytes(b"y" * 4)
    prom._dir_bytes_cache.clear()
    calls = {"n": 0}
    real_walk = _os.walk

    def counting_walk(*a, **kw):
        calls["n"] += 1
        return real_walk(*a, **kw)

    monkeypatch.setattr(prom.os, "walk", counting_walk)
    assert prom._store_dir_bytes(str(tmp_path)) == (10, 4)
    assert prom._store_dir_bytes(str(tmp_path)) == (10, 4)
    assert calls["n"] == 1, "second scrape inside the TTL re-walked"
    # expiry: age the cache entry past the TTL -> one more walk
    ts, val = prom._dir_bytes_cache[str(tmp_path)]
    prom._dir_bytes_cache[str(tmp_path)] = (
        ts - prom._DIR_BYTES_TTL_S - 1, val)
    prom._store_dir_bytes(str(tmp_path))
    assert calls["n"] == 2
    prom._dir_bytes_cache.clear()


def test_retry_policy_honors_classification():
    """err-retry-class fix: retryability is an explicit table now.
    Only RESOURCE_EXHAUSTED (a pre-work refusal, duplication-safe)
    retries; NOT_FOUND and a mid-call UNAVAILABLE (which may have
    landed a mutation without a response) fail on the first attempt."""
    from hstream_tpu.client.retry import RetryPolicy, is_retryable

    class FakeErr(grpc.RpcError):
        def __init__(self, code):
            self._code = code

        def code(self):
            return self._code

        def details(self):
            return ""

        def trailing_metadata(self):
            return ()

    assert is_retryable(grpc.StatusCode.RESOURCE_EXHAUSTED)
    assert not is_retryable(grpc.StatusCode.NOT_FOUND)
    assert not is_retryable(grpc.StatusCode.INTERNAL)
    # a mid-call transport drop may have landed a mutation: a blind
    # resend could duplicate it, so it is classified non-retryable
    assert not is_retryable(grpc.StatusCode.UNAVAILABLE)

    attempts = {"n": 0}

    def throttled():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise FakeErr(grpc.StatusCode.RESOURCE_EXHAUSTED)
        return "ok"

    pol = RetryPolicy(attempts=5, sleep=lambda s: None)
    assert pol.call(throttled) == "ok"
    assert pol.retries == 2

    for code in (grpc.StatusCode.NOT_FOUND, grpc.StatusCode.UNAVAILABLE):
        attempts["n"] = 0

        def hard():
            attempts["n"] += 1
            raise FakeErr(code)

        with pytest.raises(grpc.RpcError):
            pol.call(hard)
        assert attempts["n"] == 1, f"{code} must not retry"


# ---- ISSUE 7: defects found by the kernel-contract passes -------------------


def test_compact_codes_fetches_once_and_preserves_results():
    """dispatch-sync fix: device-mode _compact_codes fetched each
    side's code plane in a per-side loop (two round trips on the
    ingest path); it now stacks both sides into ONE transfer. The
    compaction must still remap codes exactly — results after a manual
    compaction match the host reference run bit-for-bit."""
    from tests.test_join_device import (
        final_changes,
        gen_batches,
        make_join,
        run_batches,
    )

    batches = gen_batches(seed=23, n_batches=10)
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))

    dev = make_join()
    out = []
    for i, (rows, ts, side) in enumerate(batches):
        out.extend(dev.process(rows, ts, stream=side))
        if i == 6:
            assert dev._dev is not None, "device path not active yet"
            dev._compact_codes()  # forced mid-stream compaction
    out.extend(dev.flush_changes())
    assert final_changes(out) == href


def test_migrate_store_int32_span_guard():
    """overflow-narrowing fix: device activation migrates host stores
    with `(st.ts - t0).astype(np.int32)` — the host store's 2^41 span
    guard allows ranges int32 cannot hold, so a join whose retention
    spans > 2^31 ms must trip the guard at activation instead of
    silently wrapping every probe bound. Since ISSUE 8 the tripped
    guard degrades the QUERY to the retained host reference path
    (which allows the full 2^41 span exactly) rather than killing it:
    `_activate_device` still raises SQLCodegenError loudly, but
    `_device_ready` catches it, counts device_fallbacks, and the join
    keeps producing correct results on the host path."""
    from hstream_tpu.common.errors import SQLCodegenError
    from tests.test_join_device import BASE, make_join

    # WITHIN 30000000s ~ 3e10 ms: retention exceeds int32 range
    sql = ("SELECT l.k, COUNT(*) AS c, SUM(l.x) AS s FROM l INNER "
           "JOIN r WITHIN (INTERVAL 30000000 SECOND) ON l.k = r.k "
           "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    ex = make_join(sql=sql)
    rows = [{"k": "k0", "x": 1.0}]
    # two resident entries > 2^31 ms apart (both within retention)
    ex.process(rows, [BASE], stream="l")
    ex.process(rows, [BASE + (1 << 31) + 500_000], stream="l")
    # first match builds the inner executor and plans the fast path
    ex.process(rows, [BASE + (1 << 31) + 600_000], stream="r")
    # the migration layer fails LOUDLY on the un-narrowable span ...
    fast = ex._fast_info()
    assert fast is not None, "fast path did not plan"
    with pytest.raises(SQLCodegenError, match="int32"):
        ex._activate_device(fast)
    # activation is not exception-atomic; _device_ready's except
    # clause owns the cleanup on the real path — undo the partial
    # activation so the retry below goes through it
    ex._dev = None
    # ... and the query layer degrades to the host path instead of
    # dying: the next batch retries activation through _device_ready,
    # catches the guard, and carries on exactly
    out = ex.process(rows, [BASE + (1 << 31) + 700_000], stream="r")
    assert ex.device_fallbacks == 1
    assert ex.use_device_join is False and ex._dev is None
    assert out is not None
    ex.process(rows, [BASE + (1 << 31) + 800_000], stream="r")
    assert ex.device_fallbacks == 1  # no re-activation attempts


def test_measure_rtt_jit_is_memoized():
    """retrace-uncached-jit fix: bench.measure_rtt built a fresh
    jax.jit wrapper per call; the kernel now comes from an lru_cache
    factory, so repeated calls reuse ONE compiled executable."""
    import bench
    from hstream_tpu.common.tracing import RetraceGuard

    assert bench._rtt_step() is bench._rtt_step()
    bench.measure_rtt()  # warm (compiles once)
    with RetraceGuard() as g:
        bench.measure_rtt()
        bench.measure_rtt()
    assert g.count == 0, "measure_rtt retraced after warmup"


# ---- ISSUE 8: fault injection + self-healing hardening ----------------------


def test_file_checkpoint_store_corrupt_json_recovers_boot(tmp_path):
    """FileCheckpointStore.__init__ did a bare json.load — a truncated
    or torn file raised at construction and prevented server boot. It
    must now recover to an EMPTY store (readers rewind to their trim
    points), preserve the corrupt bytes next to the path, and record
    load_error so the owner can journal checkpoint_corrupt."""
    from hstream_tpu.store import FileCheckpointStore

    path = str(tmp_path / "ckp.json")
    torn = b'{"query-q1": {"7": 123}, "query-q2": {"8"'
    with open(path, "wb") as f:
        f.write(torn)
    st = FileCheckpointStore(path)  # must NOT raise
    assert st.load_error is not None
    assert st.get("query-q1", 7) is None  # empty: rewind, not guess
    with open(path + ".corrupt", "rb") as f:
        assert f.read() == torn  # forensic copy preserved
    # the store works after recovery and persists durably again
    st.update("query-q1", 7, 55)
    assert FileCheckpointStore(path).get("query-q1", 7) == 55


def test_file_checkpoint_store_non_dict_root_recovers(tmp_path):
    """A valid-JSON-but-wrong-shape file (e.g. a list) is corruption
    too: recover empty instead of exploding on the first .items()."""
    from hstream_tpu.store import FileCheckpointStore

    path = str(tmp_path / "ckp.json")
    with open(path, "w") as f:
        f.write("[1, 2, 3]")
    st = FileCheckpointStore(path)
    assert st.load_error is not None
    assert st.get("query-x", 1) is None


def test_follower_reconnect_backoff_grows_jittered_capped():
    """_Follower._run retried a dead peer every fixed 1s — a flapping
    follower now gets jittered exponential backoff: strictly growing
    waits (2x steps beat the 25% jitter), a hard cap, a seeded
    per-address jitter stream (chaos runs replay identical waits), and
    a reset once a connect succeeds."""
    from hstream_tpu.store.replica import (
        _RETRY_CAP_S,
        _RETRY_JITTER,
        _RETRY_S,
        _Follower,
    )

    f = _Follower("127.0.0.1:19999", owner=None)
    waits = [f._backoff() for _ in range(12)]
    lo, hi = 1 - _RETRY_JITTER, 1 + _RETRY_JITTER
    assert _RETRY_S * lo <= waits[0] <= _RETRY_S * hi
    for a, b in zip(waits, waits[1:6]):
        assert b > a  # growth dominates jitter until the cap
    for w in waits[8:]:
        assert _RETRY_CAP_S * lo <= w <= _RETRY_CAP_S * hi
    # seeded per address: a rebuilt follower replays the same waits
    rebuilt = _Follower("127.0.0.1:19999", owner=None)
    assert [rebuilt._backoff() for _ in range(12)] == waits
    # an acked Replicate resets the schedule (what _stream does on
    # progress — a peer that merely ACCEPTS connections but fails
    # every Replicate keeps backing off)
    f.connect_attempts = 0
    assert f._backoff() <= _RETRY_S * hi


def test_try_adopt_race_yields_exactly_one_owner():
    """Two successor contexts racing the meta CAS for the same dead
    owner's query: exactly one may win; the loser must journal an
    adoption_lost event and stand down (return False). The barrier
    holds both racers between their config read and their CAS write,
    so both see the same base version — the true race interleaving."""
    from hstream_tpu.server import scheduler
    from hstream_tpu.server.context import ServerContext
    from hstream_tpu.store import open_store
    from hstream_tpu.store.versioned import VersionedConfigStore

    store = open_store("mem://")
    dead = ServerContext(store)
    scheduler.record_assignment(dead, "q-race")  # the owner that died
    a = ServerContext(store, persistence=dead.persistence)
    b = ServerContext(store, persistence=dead.persistence)
    assert dead.boot_epoch < a.boot_epoch < b.boot_epoch

    barrier = threading.Barrier(2, timeout=10)
    orig_put = VersionedConfigStore.put

    def racing_put(self, *args, **kwargs):
        barrier.wait()  # both racers read before either writes
        return orig_put(self, *args, **kwargs)

    results = {}

    def race(name, ctx):
        results[name] = scheduler.try_adopt(ctx, "q-race")

    VersionedConfigStore.put = racing_put
    try:
        ta = threading.Thread(target=race, args=("a", a))
        tb = threading.Thread(target=race, args=("b", b))
        ta.start(); tb.start(); ta.join(10); tb.join(10)
    finally:
        VersionedConfigStore.put = orig_put
    assert sorted(results.values()) == [False, True], results
    winner, loser = (a, b) if results["a"] else (b, a)
    # the winner's claim stands in the config store
    owner = scheduler.assignment(winner, "q-race")
    assert owner["epoch"] == winner.boot_epoch
    # the loser journaled its stand-down for the operator timeline
    lost = loser.events.query(kind="adoption_lost")
    assert lost and lost[-1]["query"] == "q-race"
    assert not winner.events.query(kind="adoption_lost")


class _SupPersistence:
    """Minimal persistence for QuerySupervisor unit tests: every query
    reads back RUNNING (never terminated while pending)."""

    def get_query(self, qid):
        from hstream_tpu.server.persistence import QueryInfo, TaskStatus

        return QueryInfo(qid, "select 1", 0, status=TaskStatus.RUNNING)

    def set_query_status(self, qid, status):
        pass


class _SupCtx:
    def __init__(self):
        self.running_queries = {}
        self.persistence = _SupPersistence()


def test_supervisor_corpse_teardown_requeues_instead_of_dropping():
    """note_death fires from the dying task's except block, but the
    corpse pops running_queries LAST — its finally joins reader/persist
    threads, which can outlast the ~0.2s first backoff. A restart
    attempt that finds the dead task (``.error`` set) still registered
    must requeue, not mistake the corpse for a live operator-owned task
    and drop the restart forever; a task without ``.error`` really is
    operator-owned and the restart stands down."""
    from hstream_tpu.server.persistence import QueryInfo
    from hstream_tpu.server.scheduler import QuerySupervisor

    class _Corpse:
        error = RuntimeError("died mid-batch")

    class _OperatorTask:
        error = None

    ctx = _SupCtx()
    clock = [100.0]
    sup = QuerySupervisor(ctx, clock=lambda: clock[0])
    resumed = []
    sup.resume_fn = resumed.append
    info = QueryInfo("q-corpse", "select 1", 0)

    ctx.running_queries["q-corpse"] = _Corpse()
    sup._attempt_restart("q-corpse", info, 1)
    assert not resumed
    assert "q-corpse" in sup.status()["pending"]  # requeued, not lost
    # corpse finished tearing down: the requeued attempt lands
    sup._pending.pop("q-corpse")  # what the loop does at dispatch
    del ctx.running_queries["q-corpse"]
    sup._attempt_restart("q-corpse", info, 1)
    assert [i.query_id for i in resumed] == ["q-corpse"]
    assert sup.restarts == 1
    # a LIVE operator-started task (no .error) keeps ownership
    ctx.running_queries["q-corpse"] = _OperatorTask()
    sup._attempt_restart("q-corpse", info, 2)
    assert len(resumed) == 1
    assert "q-corpse" not in sup.status()["pending"]


def test_supervisor_cancel_waits_out_inflight_restart():
    """TerminateQuery racing an executing restart: the restart is
    marked in-flight when it is popped from pending, and cancel()
    blocks until it finishes — so the terminate path always runs AFTER
    any resurrect and the task it pops from running_queries is the
    final one (no deleted query springing back to RUNNING)."""
    from hstream_tpu.server.persistence import QueryInfo
    from hstream_tpu.server.scheduler import QuerySupervisor

    release = threading.Event()
    in_resume = threading.Event()

    def resume(info):
        in_resume.set()
        assert release.wait(5)

    sup = QuerySupervisor(_SupCtx(), resume_fn=resume)
    sup.BACKOFF_BASE_S = 0.01
    sup.BACKOFF_CAP_S = 0.05
    try:
        sup.note_death(QueryInfo("q-term", "select 1", 0))
        assert in_resume.wait(5), sup.status()
        cancel_done = threading.Event()

        def terminate():
            sup.cancel("q-term")
            cancel_done.set()

        t = threading.Thread(target=terminate)
        t.start()
        # the restart is still executing: cancel must not return yet
        assert not cancel_done.wait(0.3)
        release.set()
        assert cancel_done.wait(5)  # returns once the resurrect landed
        t.join(5)
        assert sup.status()["pending"] == {}
        assert sup.restarts == 1
    finally:
        release.set()
        sup.shutdown()


def test_query_labeled_counters_survive_live_stream_filter():
    """/metrics liveness filter vs query-labeled counters: the
    query_restarts / snapshot_fallbacks series are labeled by QUERY id,
    which is never a live stream name — the filter silently dropped
    them from the exposition (found by the PR 8 verify drive: a
    supervised restart bumped the counter but /metrics showed no
    series). They are exempt from the STREAM filter, like "_"-prefixed
    pseudo-streams, but bounded by QUERY existence instead — a deleted
    query's series must not grow the exposition forever."""
    from hstream_tpu.stats import StatsHolder
    from hstream_tpu.stats.prometheus import render_holder

    stats = StatsHolder()
    stats.stream_stat_add("query_restarts", "view-v1")
    stats.stream_stat_add("snapshot_fallbacks", "view-v1")
    stats.stream_stat_add("device_path_fallbacks", "src")   # stream-labeled
    stats.stream_stat_add("device_path_fallbacks", "gone")  # deleted stream
    text = render_holder(stats, live_streams={"src"})
    assert 'hstream_query_restarts_total{stream="view-v1"} 1' in text
    assert 'hstream_snapshot_fallbacks_total{stream="view-v1"} 1' in text
    assert 'hstream_device_path_fallbacks_total{stream="src"} 1' in text
    assert '"gone"' not in text  # liveness filter still applies
    # the query-labeled exemption is bounded by query existence: a
    # still-persisted (even FAILED) query keeps its series, a DELETED
    # query's series are pruned from the scrape
    text = render_holder(stats, live_streams={"src"},
                         live_queries={"view-v1"})
    assert 'hstream_query_restarts_total{stream="view-v1"} 1' in text
    text = render_holder(stats, live_streams={"src"}, live_queries=set())
    assert '"view-v1"' not in text


# ---- ISSUE 9: epoch-fenced failover hardening -------------------------------


def test_supervisor_stands_down_on_leadership_loss():
    """A task that dies of NotLeaderError must NOT be restart-looped:
    this node's store was fenced, every restart would die identically
    and burn the crash-loop breaker. The supervisor stands down
    (journaling the fencing) and leaves the replicated RUNNING record
    for the new leader's boot to adopt; ordinary deaths still
    schedule restarts."""
    from hstream_tpu.common.errors import NotLeaderError
    from hstream_tpu.server.persistence import QueryInfo
    from hstream_tpu.server.scheduler import QuerySupervisor
    from hstream_tpu.stats.events import EventJournal

    ctx = _SupCtx()
    ctx.events = EventJournal()
    sup = QuerySupervisor(ctx)
    info = QueryInfo("q-fenced", "select 1", 0)
    try:
        for _ in range(10):  # repeated fencing never opens the breaker
            sup.note_death(info, NotLeaderError(
                "store leadership lost", leader_hint="new:1"))
        st = sup.status()
        assert st["pending"] == {}
        assert st["breaker_open"] == []
        assert st["restarts"] == 0
        events = ctx.events.query(kind="replica_fenced", limit=20)
        assert events and events[0]["leader_hint"] == "new:1"
        # a plain crash on the same query still schedules a restart
        sup.note_death(info, RuntimeError("boom"))
        assert "q-fenced" in sup.status()["pending"]
    finally:
        sup.shutdown()


def test_supervisor_status_pending_is_sorted():
    """Operator/chaos assertions diff `admin supervisor` output: the
    pending map must come back sorted by query id, not in death
    order."""
    from hstream_tpu.server.persistence import QueryInfo
    from hstream_tpu.server.scheduler import QuerySupervisor

    ctx = _SupCtx()
    clock = [100.0]
    sup = QuerySupervisor(ctx, clock=lambda: clock[0])
    try:
        for qid in ("q-z", "q-a", "q-m"):
            sup.note_death(QueryInfo(qid, "select 1", 0))
        assert list(sup.status()["pending"]) == ["q-a", "q-m", "q-z"]
    finally:
        sup.shutdown()


def test_replica_divergence_checked_before_mutation():
    """_apply must detect an LSN mismatch BEFORE appending: the old
    order landed the batch and then raised, so every sender retry of
    the same entry grew the diverged replica's log further."""
    import pytest

    from hstream_tpu.common.errors import ReplicaDivergence
    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import _apply

    st = open_store("mem://")
    st.create_log(9)
    st.append(9, b"existing")
    e = pb.LogEntry(op=pb.OP_APPEND, logid=9, payloads=[b"x"],
                    expect_lsn=5)  # tail is 1; 5 expects tail 4
    for _ in range(3):  # retries must not mutate either
        with pytest.raises(ReplicaDivergence):
            _apply(st, e)
    assert st.tail_lsn(9) == 1  # nothing landed
    st.close()

def test_dedup_seq_zero_first_append_accepted():
    """Review fix: the empty dedup watermark is -1, not 0 — seq 0 is a
    legal first stamp (and the proto3 default when only producer_id is
    set), so a 0-based producer's very first append must be accepted,
    not refused ALREADY_EXISTS as an evicted duplicate."""
    from hstream_tpu.store import dedup, open_store

    st = open_store("mem://")
    assert dedup.lookup(st, "p-zero", 0) is None  # new, not duplicate
    dedup.record(st, "p-zero", 0, 17, 3)
    assert dedup.lookup(st, "p-zero", 0) == (17, 3)  # now remembered
    st.close()


def test_malformed_producer_seq_refused_not_unstamped():
    """Review fix: a stamped ExecuteQuery whose x-producer-seq does not
    parse must be refused INVALID_ARGUMENT — silently running the
    INSERT unstamped would let the client's retry double-append while
    it believes it has exactly-once."""
    import pytest

    from hstream_tpu.common.errors import SQLValidateError
    from hstream_tpu.server.handlers import _producer_from

    class _Ctx:
        def __init__(self, md):
            self._md = md

        def invocation_metadata(self):
            return self._md

    with pytest.raises(SQLValidateError):
        _producer_from(_Ctx([("x-producer-id", "p1"),
                             ("x-producer-seq", "0x2a")]))
    # well-formed stamp still parses; absent stamp still None
    assert _producer_from(_Ctx([("x-producer-id", "p1"),
                                ("x-producer-seq", "42")])) == ("p1", 42)
    assert _producer_from(_Ctx([])) is None


def test_auto_promote_lease_floored_above_heartbeat():
    """Review fix: a lease below the idle-heartbeat cadence would fence
    a healthy idle leader between two heartbeats — FollowerService
    clamps it to 3x _HEARTBEAT_S."""
    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import _HEARTBEAT_S, FollowerService

    st = open_store("mem://")
    svc = FollowerService(st, node_id="floor-f", lease_timeout_s=0.05)
    try:
        assert svc.lease_timeout_s == _HEARTBEAT_S * 3
    finally:
        svc.close()
        st.close()


def test_auto_promotion_hint_prefers_advertise_addr():
    """Review fix: the auto-promotion leader hint must be the
    client-facing SQL address (--advertise-addr), not the replica's
    StoreReplica listen port — a client following the raw replica
    address would fail UNIMPLEMENTED."""
    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import FollowerService

    st = open_store("mem://")
    svc = FollowerService(st, node_id="adv-f", listen_addr="repl:1",
                          advertise_addr="sql:1")
    try:
        svc._promote_locked(1, "", "lease-timeout")
        assert svc._leader_hint == "sql:1"
        info = svc.ReplicaInfo(pb.ReplicaInfoRequest(), None)
        assert info.leader_hint == "sql:1"
    finally:
        svc.close()
        st.close()


def test_gateway_rebind_retires_old_channel_instead_of_closing():
    """Review fix: the gateway's leader-hint rebind must not close the
    shared channel out from under concurrent handler threads mid-RPC —
    the old channel is retired and closed only at gateway shutdown."""
    from hstream_tpu.http_gateway import Gateway

    gw = Gateway("127.0.0.1:1")
    old = gw.channel
    gw._follow_leader_hint("127.0.0.1:2")
    assert gw.server_addr == "127.0.0.1:2"
    assert gw.channel is not old and gw._retired == [old]
    assert gw.leader_follows == 1
    # same-hint re-follow is a no-op (concurrent callers rebind once)
    gw._follow_leader_hint("127.0.0.1:2")
    assert gw.leader_follows == 1
    gw.close()
    assert gw._retired == []


# ---- ISSUE 14: concurrency certification ------------------------------------
#
# The three new passes (lockorder/atomicity/waitholding) came up CLEAN
# on the tree — the expected candidates (supervisor corpse/cancel,
# gateway rebind, append-front close) had been fixed by hand in the
# PR 8/11 review rounds, and the passes now pin those shapes via
# fixtures in test_analyze. What this section pins is the live-tree
# contracts behind that verdict: the canonical lock ORDER the static
# graph documents, the one reviewed waiver, and the witness's
# disarmed-cost contract on the real instrumented subsystems.


def test_lockorder_real_tree_graph_acyclic_with_canonical_edges():
    """The whole-program lock graph of THIS tree resolves the
    documented cross-object orders (tasks.state before
    views.materialization via Materialization.snapshot; the scrape
    lock before the gauge internals) and stays acyclic. If the
    cross-class typing regresses these edges vanish; if someone
    introduces an inversion the cycle list goes non-empty — both fail
    here before CI's analyze step even runs."""
    import os
    import sys

    REPO_ROOT = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    sys.path.insert(0, REPO_ROOT)
    from tools.analyze import load_tree
    from tools.analyze.passes import conc, lockorder

    files = load_tree(REPO_ROOT)
    prog = conc.build_program(files)
    edges = lockorder._collect_edges(files, prog)
    got = set(edges)
    assert ("QueryTask.state_lock", "Materialization._lock") in got
    assert ("StatsHolder.scrape_lock", "StatsHolder._gauge_lock") in got
    assert lockorder._cycles(edges) == []


def test_witness_certifies_task_before_materialization_order():
    """The live (armed) witness observes the canonical order on the
    REAL objects: sink-under-state_lock then snapshot both take
    tasks.state before views.materialization — one direction, no
    cycle, and the ledger carries both lock roles."""
    from hstream_tpu.common import locktrace
    from hstream_tpu.common.locktrace import LOCKTRACE

    LOCKTRACE.disarm()
    LOCKTRACE.arm()
    try:
        mat = Materialization(group_cols=["k"])

        class _Task:
            state_lock = locktrace.rlock("tasks.state")
            executor = None

        task = _Task()
        mat.task = task
        # the sink path: task emits closed rows under its state lock
        with task.state_lock:
            mat.add_closed([{"k": "a", "winStart": 1}])
        # the pull path: snapshot takes state_lock then mat._lock
        assert mat.snapshot() == [{"k": "a", "winStart": 1}]
        st = LOCKTRACE.status()
        assert st["edges"].get("tasks.state") == \
            ["views.materialization"]
        assert "views.materialization" not in st["edges"]
        assert st["cycles"] == []
        assert {"tasks.state", "views.materialization"} <= \
            set(st["locks"])
    finally:
        LOCKTRACE.disarm()


def test_witness_disarmed_records_nothing_on_real_subsystems():
    """Disarmed-cost contract on the real instrumented objects: a
    subscription-registry + materialization + supervisor workout with
    the witness disarmed leaves ZERO witness state."""
    from hstream_tpu.common.locktrace import LOCKTRACE
    from hstream_tpu.server.subscriptions import SubscriptionRegistry

    LOCKTRACE.disarm()
    reg = SubscriptionRegistry()
    assert reg.exists("nope") is False
    mat = Materialization(group_cols=["k"])
    mat.add_closed([{"k": "a", "winStart": 1}])
    assert mat.dump() == [{"k": "a", "winStart": 1}]
    st = LOCKTRACE.status()
    assert st["locks"] == {} and st["edges"] == {} \
        and st["cycles"] == []


# ---- ISSUE 16: multi-chip exclusions retired for JOIN + sessions ------------


def test_mesh_exclusions_join_and_sessions_retired():
    """Interval joins and session windows are mesh-sharded since
    ISSUE 16: the retired exclusion strings must be GONE from the
    shared predicate (source pin — a revert would resurrect them
    silently, EXPLAIN and the runtime gate share the predicate),
    while the two remaining exclusions (TOPK planes, stream-TABLE
    joins) must still fire."""
    import inspect

    from hstream_tpu.sql import codegen as cg

    src = inspect.getsource(cg)
    # retired with the sharded join/session lattices
    assert "two-sided host state" not in src
    assert "single-chip session lattice" not in src
    assert "sharded execution of JOIN plans is not supported" not in src

    plan = cg.stream_codegen(
        "SELECT l.k, COUNT(*) AS c FROM l INNER JOIN r "
        "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k GROUP BY l.k, "
        "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    assert cg.mesh_exclusion_reason(plan) is None
    assert "MESH: shardable" in cg.explain_text(plan)

    plan = cg.stream_codegen(
        "SELECT k, COUNT(*) AS c FROM s GROUP BY k, "
        "SESSION (INTERVAL 5 SECOND) EMIT CHANGES;")
    assert cg.mesh_exclusion_reason(plan) is None
    assert "MESH: shardable" in cg.explain_text(plan)

    # the remaining exclusions stay pinned PRESENT
    plan = cg.stream_codegen(
        "SELECT k, TOPK(v, 3) AS t FROM s GROUP BY k, "
        "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    reason = cg.mesh_exclusion_reason(plan)
    assert reason is not None and "TOPK" in reason

    plan = cg.stream_codegen(
        "SELECT l.k, COUNT(*) AS c FROM l INNER JOIN TABLE(t) "
        "ON l.k = t.k GROUP BY l.k, "
        "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;")
    reason = cg.mesh_exclusion_reason(plan)
    assert reason is not None and "stream-TABLE" in reason


# ---- ISSUE 19: protocol certification — triage verdicts pinned --------------
#
# The casdiscipline/timeunit triage found NO true positives on the live
# tree: every finding is a reviewed single-writer exception in
# store/replica.py (waivers pinned load-bearing in test_analyze.py).
# What the waivers LEAN ON is behavior, so the behavior is pinned here:
# each test below is the runtime fact that makes one reviewed waiver
# (or one certified invariant) sound.


def test_placer_lease_clamped_to_three_intervals():
    """The clamp the `cas-lease-raw` rule protects: a lease below 3x
    the placer tick is raised at construction, so no age comparison
    ever runs against a sub-interval lease."""
    from hstream_tpu.placer.core import Placer

    p = Placer(object(), interval_ms=2000, lease_ms=2000)
    assert p.armed and p.lease_ms == 6000
    # a sane lease is untouched, and a disarmed placer never clamps
    assert Placer(object(), interval_ms=1000, lease_ms=5000).lease_ms \
        == 5000
    assert Placer(object(), interval_ms=None, lease_ms=2000).lease_ms \
        == 2000


def test_live_adoption_refuses_fresh_heartbeat():
    """The fresh-lease refusal in try_adopt_live (protocheck mutant
    `fresh-heartbeat-refusal`): an adopt sweep must NOT seize a query
    whose owner heartbeated within the lease."""
    from tools.protocheck.model import SCENARIOS, Model

    model = Model(SCENARIOS["kill-2"])
    with model.engaged():
        pre = model.sched_records()
        model.execute(("adopt", 0))
        post = model.sched_records()
        # both records untouched: every owner's heartbeat is 0ms old
        assert {q: r for q, (_raw, r) in post.items()} == \
            {q: r for q, (_raw, r) in pre.items()}


def test_promote_epoch_guard_keeps_durable_epoch():
    """The guard backing the `cas-epoch-nonmonotone` waiver on
    `_promote_locked`: Promote refuses epoch <= current BEFORE the
    bare assignment runs, so the durable epoch never moves backwards
    even though the write itself is unguarded."""
    from hstream_tpu.proto import api_pb2 as pb
    from tools.protocheck.replica_model import MiniLogStore, _GrpcCtx

    from hstream_tpu.store.replica import META_EPOCH, FollowerService

    f = FollowerService(MiniLogStore(), node_id="r1")
    ok = f.Promote(pb.PromoteRequest(epoch=2, leader_addr="a",
                                     promoted_by="t"), _GrpcCtx())
    assert ok.ok and f.epoch == 2
    again = f.Promote(pb.PromoteRequest(epoch=2, leader_addr="b",
                                        promoted_by="t"), _GrpcCtx())
    assert not again.ok
    assert f.epoch == 2 and f.local.meta_get(META_EPOCH) == b"2"


def test_fenced_replicate_leaves_binding_writes_unrun():
    """The fence backing the `cas-blind-meta-write` waivers in
    `_accept_leader_locked`: a stale-epoch Replicate is refused before
    ANY of the blind single-writer meta writes run, so the durable
    binding only ever changes under an accepted (higher-epoch)
    leader."""
    from hstream_tpu.proto import api_pb2 as pb
    from tools.protocheck.replica_model import MiniLogStore, _GrpcCtx

    from hstream_tpu.store.replica import FollowerService

    store = MiniLogStore()
    f = FollowerService(store, node_id="r1")
    r = f.Replicate(pb.ReplicateRequest(epoch=3, leader_id="L3"),
                    _GrpcCtx())
    assert not r.fenced
    before = store.fingerprint()
    stale = f.Replicate(pb.ReplicateRequest(epoch=2, leader_id="L2"),
                        _GrpcCtx())
    assert stale.fenced and stale.epoch == 3
    assert store.fingerprint() == before
