"""Server e2e tests: gRPC black-box against a real in-process server
(the reference's HandlerSpec / RunSQLSpec tier, hstream/test)."""

import threading
import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_any_attached, wait_attached

BASE = 1_700_000_000_000


@pytest.fixture(scope="module")
def server_stub():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    yield stub, ctx
    channel.close()
    server.stop(grace=1)
    ctx.shutdown()


def append_rows(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    return stub.Append(req)


def test_echo_and_nodes(server_stub):
    stub, ctx = server_stub
    assert stub.Echo(pb.EchoRequest(msg="hi")).msg == "hi"
    nodes = stub.ListNodes(pb.ListNodesRequest()).nodes
    assert len(nodes) == 1 and nodes[0].status == "Running"


def test_stream_crud_and_append(server_stub):
    stub, _ = server_stub
    stub.CreateStream(pb.Stream(stream_name="crud", replication_factor=1))
    with pytest.raises(grpc.RpcError) as ei:
        stub.CreateStream(pb.Stream(stream_name="crud"))
    assert ei.value.code() == grpc.StatusCode.ALREADY_EXISTS
    names = [s.stream_name
             for s in stub.ListStreams(pb.ListStreamsRequest()).streams]
    assert "crud" in names
    resp = append_rows(stub, "crud", [{"a": 1}, {"a": 2}],
                       [BASE, BASE + 1])
    assert len(resp.record_ids) == 2
    assert resp.record_ids[0].batch_id == resp.record_ids[1].batch_id
    stub.DeleteStream(pb.DeleteStreamRequest(stream_name="crud"))
    names = [s.stream_name
             for s in stub.ListStreams(pb.ListStreamsRequest()).streams]
    assert "crud" not in names


def test_execute_query_ddl_insert_show_explain(server_stub):
    stub, _ = server_stub
    stub.ExecuteQuery(pb.CommandQuery(stmt_text="CREATE STREAM ddl1;"))
    rows = stub.ExecuteQuery(
        pb.CommandQuery(stmt_text="SHOW STREAMS;")).result_set
    assert any(r["stream"] == "ddl1" for r in
               (rec.struct_to_dict(s) for s in rows))
    r = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text='INSERT INTO ddl1 (a, b) VALUES (1, \'x\');'))
    assert rec.struct_to_dict(r.result_set[0])["lsn"] >= 1
    ex = stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="EXPLAIN SELECT COUNT(*) FROM ddl1 GROUP BY k "
                  "EMIT CHANGES;"))
    assert "AGGREGATE" in rec.struct_to_dict(ex.result_set[0])["explain"]


def test_push_query_end_to_end(server_stub):
    """CREATE STREAM -> push query -> INSERT -> windowed aggregates stream
    back -> TERMINATE stops it (reference Handler.hs:349-415 flow)."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="weather"))
    got: list[dict] = []
    pre_existing = set(ctx.running_queries)
    started = threading.Event()

    def consume():
        call = stub.ExecutePushQuery(pb.CommandPushQuery(
            query_text="SELECT city, COUNT(*) AS c FROM weather "
                       "GROUP BY city, TUMBLING (INTERVAL 10 SECOND) "
                       "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
        started.set()
        try:
            for s in call:
                got.append(rec.struct_to_dict(s))
        except grpc.RpcError:
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    started.wait(5)
    wait_any_attached(ctx, exclude=pre_existing)  # new task attached
    append_rows(stub, "weather",
                [{"city": "sf", "temp": 1.0}, {"city": "sf", "temp": 2.0},
                 {"city": "la", "temp": 3.0}],
                [BASE, BASE + 100, BASE + 200])
    def _seen():
        # wait for BOTH cities: a window's rows may stream back in
        # separate chunks, so the la row can trail the sf row
        return (any(r.get("city") == "sf" and r.get("c") == 2
                    for r in got)
                and any(r.get("city") == "la" and r.get("c") == 1
                        for r in got))

    deadline = time.time() + 30
    while time.time() < deadline and not _seen():
        time.sleep(0.2)
    assert _seen(), got
    # terminate all push queries; the consumer loop must end
    stub.TerminateQueries(pb.TerminateQueriesRequest(all=True))
    t.join(15)
    assert not t.is_alive()


def test_query_lifecycle(server_stub):
    stub, _ = server_stub
    stub.CreateStream(pb.Stream(stream_name="lifec"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        id="lq1", query_text="SELECT k, COUNT(*) AS c FROM lifec "
                             "GROUP BY k EMIT CHANGES;"))
    assert q.id == "lq1"
    ids = [x.id for x in stub.ListQueries(pb.ListQueriesRequest()).queries]
    assert "lq1" in ids
    got = stub.GetQuery(pb.GetQueryRequest(id="lq1"))
    assert got.query_text.startswith("SELECT")
    resp = stub.TerminateQueries(
        pb.TerminateQueriesRequest(query_ids=["lq1"]))
    assert list(resp.query_ids) == ["lq1"]
    deadline = time.time() + 10
    while time.time() < deadline:
        if stub.GetQuery(pb.GetQueryRequest(id="lq1")).status == 4:
            break
        time.sleep(0.1)
    assert stub.GetQuery(pb.GetQueryRequest(id="lq1")).status == 4
    # restart resumes it (the reference leaves RestartQuery unimplemented)
    stub.RestartQuery(pb.RestartQueryRequest(id="lq1"))
    assert stub.GetQuery(pb.GetQueryRequest(id="lq1")).status == 3
    stub.DeleteQuery(pb.DeleteQueryRequest(id="lq1"))
    with pytest.raises(grpc.RpcError) as ei:
        stub.GetQuery(pb.GetQueryRequest(id="lq1"))
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_subscription_fetch_ack(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="subs"))
    sub = pb.Subscription(subscription_id="sub1", stream_name="subs")
    stub.CreateSubscription(sub)
    assert stub.CheckSubscriptionExist(
        pb.CheckSubscriptionExistRequest(subscription_id="sub1")).exists
    append_rows(stub, "subs", [{"n": i} for i in range(5)],
                [BASE + i for i in range(5)])
    got = stub.Fetch(pb.FetchRequest(subscription_id="sub1",
                                     timeout_ms=2000, max_size=64))
    assert len(got.received_records) == 5
    recs = [rec.parse_record(r.record) for r in got.received_records]
    assert rec.record_to_dict(recs[0]) == {"n": 0}
    # ack all -> checkpoint commits
    stub.Acknowledge(pb.AcknowledgeRequest(
        subscription_id="sub1",
        ack_ids=[r.record_id for r in got.received_records]))
    rt = ctx.subscriptions.get("sub1")
    assert rt.committed_lsn >= got.received_records[0].record_id.batch_id
    stub.DeleteSubscription(
        pb.DeleteSubscriptionRequest(subscription_id="sub1"))
    assert not stub.CheckSubscriptionExist(
        pb.CheckSubscriptionExistRequest(subscription_id="sub1")).exists


def test_subscription_resume_from_checkpoint(server_stub):
    """Crash/resume: a new subscription runtime resumes from the
    committed checkpoint, redelivering only unacked records."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="resume"))
    sub = pb.Subscription(subscription_id="res1", stream_name="resume")
    stub.CreateSubscription(sub)
    # two separate appends -> two batches
    append_rows(stub, "resume", [{"n": 0}], [BASE])
    append_rows(stub, "resume", [{"n": 1}], [BASE + 1])
    got = stub.Fetch(pb.FetchRequest(subscription_id="res1",
                                     timeout_ms=2000, max_size=64))
    assert len(got.received_records) == 2
    # ack only the first batch
    stub.Acknowledge(pb.AcknowledgeRequest(
        subscription_id="res1", ack_ids=[got.received_records[0].record_id]))
    rt = ctx.subscriptions.get("res1")
    assert rt.committed_lsn == got.received_records[0].record_id.batch_id
    # simulate consumer crash: drop the runtime, recreate the subscription
    ctx.subscriptions.remove("res1")
    stub.CreateSubscription(sub)
    got2 = stub.Fetch(pb.FetchRequest(subscription_id="res1",
                                      timeout_ms=2000, max_size=64))
    ns = [rec.record_to_dict(rec.parse_record(r.record))["n"]
          for r in got2.received_records]
    assert ns == [1]  # only the unacked record is redelivered


def test_view_pull_query(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="vsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW v1 AS SELECT city, COUNT(*) AS c "
                  "FROM vsrc GROUP BY city, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    views = stub.ListViews(pb.ListViewsRequest()).views
    assert any(v.view_id == "v1" for v in views)
    wait_attached(ctx, "view-v1")
    append_rows(stub, "vsrc",
                [{"city": "sf"}, {"city": "sf"}, {"city": "la"}],
                [BASE, BASE + 1, BASE + 2])
    # closer record forces the window shut (materialized as closed rows)
    append_rows(stub, "vsrc", [{"city": "xx"}], [BASE + 30_000])
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM v1 WHERE city = 'sf';"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        if any(r.get("c") == 2 and r.get("winStart") == BASE
               for r in rows):
            break
        time.sleep(0.2)
    assert any(r.get("c") == 2 and r.get("winStart") == BASE
               for r in rows), rows
    stub.DeleteView(pb.DeleteViewRequest(view_id="v1"))
    assert not any(v.view_id == "v1" for v in
                   stub.ListViews(pb.ListViewsRequest()).views)


def test_sink_connector_sqlite(server_stub, tmp_path):
    import sqlite3

    stub, _ = server_stub
    db = tmp_path / "sink.db"
    conn = sqlite3.connect(db)
    conn.execute('CREATE TABLE t (a INTEGER, b TEXT)')
    conn.commit()
    conn.close()
    stub.CreateStream(pb.Stream(stream_name="csrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text=f"CREATE SINK CONNECTOR sc1 WITH "
                  f"(type = 'sqlite', stream = 'csrc', "
                  f"path = '{db}', table = 't');"))
    append_rows(stub, "csrc", [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}],
                [BASE, BASE + 1])
    deadline = time.time() + 15
    rows = []
    while time.time() < deadline:
        conn = sqlite3.connect(db)
        rows = conn.execute("SELECT a, b FROM t ORDER BY a").fetchall()
        conn.close()
        if len(rows) == 2:
            break
        time.sleep(0.2)
    assert rows == [(1, "x"), (2, "y")]
    cs = stub.ListConnectors(pb.ListConnectorsRequest()).connectors
    assert any(c.id == "sc1" for c in cs)
    stub.DeleteConnector(pb.DeleteConnectorRequest(id="sc1"))


def test_streaming_fetch(server_stub):
    stub, _ = server_stub
    stub.CreateStream(pb.Stream(stream_name="sf_src"))
    stub.CreateSubscription(pb.Subscription(subscription_id="sf_sub",
                                            stream_name="sf_src"))
    append_rows(stub, "sf_src", [{"n": i} for i in range(3)],
                [BASE + i for i in range(3)])

    def requests():
        yield pb.StreamingFetchRequest(subscription_id="sf_sub",
                                       consumer_name="c1")
        # keep the request side open while we receive
        time.sleep(3)

    call = stub.StreamingFetch(requests())
    received = []
    deadline = time.time() + 10
    for resp in call:
        for r in resp.received_records:
            received.append(
                rec.record_to_dict(rec.parse_record(r.record))["n"])
        if len(received) >= 3 or time.time() > deadline:
            call.cancel()
            break
    assert sorted(received) == [0, 1, 2]
