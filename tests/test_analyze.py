"""Tests for the hstream-analyze static-analysis suite (ISSUE 4).

Each pass gets: a seeded violation caught in fixture code (positive),
clean fixture code producing nothing (negative), and waiver/baseline
suppression. A final full-tree run asserts the real repository carries
zero non-baselined findings — the analyzer's acceptance bar.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.analyze import (  # noqa: E402
    Finding,
    SourceFile,
    load_baseline,
    load_tree,
    run_passes,
    write_baseline,
)
from tools.analyze.passes import (  # noqa: E402
    atomicity,
    blocking,
    casdiscipline,
    dispatch,
    errcontract,
    lifecycle,
    lockorder,
    locks,
    overflow,
    purity,
    registry,
    retrace,
    shardmap,
    timeunit,
    waitholding,
)


def src(rel: str, code: str) -> SourceFile:
    return SourceFile(rel, rel, textwrap.dedent(code))


def rules_of(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


def run_one(mod, files) -> list[Finding]:
    """Run one pass and apply waivers like the framework does."""
    by_rel = {f.rel: f for f in files}
    out = []
    for f in mod.run(files, REPO):
        s = by_rel.get(f.path)
        if s is not None and s.waived(f.line, f.rule):
            continue
        out.append(f)
    return out


# ---- locks -----------------------------------------------------------------


LOCKED_CLASS = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._val = 0

    def bump(self):
        with self._lock:
            self._val += 1

    def reset(self):
        with self._lock:
            self._val = 0

    def peek(self):
        return self._val{waiver}
'''


def test_lock_guard_positive():
    out = run_one(locks, [src("m.py", LOCKED_CLASS.format(waiver=""))])
    assert rules_of(out) == {"lock-guard"}
    (f,) = out
    assert "_val" in f.message and "peek" in f.message


def test_lock_guard_waiver_suppresses():
    code = LOCKED_CLASS.format(waiver="  # analyze: ok lock-guard")
    assert run_one(locks, [src("m.py", code)]) == []


def test_lock_guard_negative_all_locked():
    code = LOCKED_CLASS.format(waiver="").replace(
        "    def peek(self):\n        return self._val",
        "    def peek(self):\n        with self._lock:\n"
        "            return self._val")
    assert run_one(locks, [src("m.py", code)]) == []


def test_lock_guard_locked_suffix_method_exempt():
    code = '''
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0

        def bump(self):
            with self._lock:
                self._val += 1
                self._flush_locked()

        def drain(self):
            with self._lock:
                self._val = 0

        def _flush_locked(self):
            self._val += 2  # runs under the caller's lock
    '''
    assert run_one(locks, [src("m.py", code)]) == []


def test_lock_guard_wrong_lock_flagged():
    """Holding a DIFFERENT lock of the same class is not protection:
    the access still races the real guard's writers."""
    code = '''
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition()
            self._val = 0

        def bump(self):
            with self._lock:
                self._val += 1

        def reset(self):
            with self._lock:
                self._val = 0

        def peek(self):
            with self._cv:          # wrong lock!
                return self._val
    '''
    out = run_one(locks, [src("m.py", code)])
    assert len(out) == 1 and out[0].rule == "lock-guard"
    assert "_cv" in out[0].message and "_lock" in out[0].message


def test_lock_order_inversion_flagged():
    code = '''
    import threading

    class Two:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def backward(self):
            with self._b_lock:
                with self._a_lock:
                    pass
    '''
    out = run_one(locks, [src("m.py", code)])
    assert rules_of(out) == {"lock-order"}
    assert len(out) == 2  # both sites named


# ---- lockorder: whole-program cycle (ISSUE 14) -----------------------------


# the seeded CROSS-CLASS inversion the per-class rule cannot see: the
# task calls into the supervisor under its own lock, the supervisor
# reaches back under ITS lock. Wiring types the `sup` attribute; the
# local constructor types `t`.
CROSS_CLASS_INVERSION = '''
import threading

class Task:
    def __init__(self):
        self.state_lock = threading.Lock()
        self.sup = None
        self.v = 0

    def die(self):
        with self.state_lock:
            self.sup.note_death(self){waiver_a}

    def poke(self):
        with self.state_lock:
            self.v += 1

class Supervisor:
    def __init__(self):
        self._lock = threading.Lock()
        self.tasks = []

    def note_death(self, t):
        with self._lock:
            self.tasks.append(t)

    def cancel(self):
        with self._lock:
            t = Task()
            t.poke()

def wire():
    t = Task()
    t.sup = Supervisor()
    return t
'''


def test_lockorder_cross_class_cycle_with_witness_path():
    out = run_one(lockorder,
                  [src("m.py", CROSS_CLASS_INVERSION.format(waiver_a=""))])
    assert rules_of(out) == {"lockorder-cycle"}
    assert len(out) == 2  # every edge of the ring is flagged
    msgs = " | ".join(f.message for f in out)
    # the full witness ring is printed, plus the per-edge call chain
    assert "Task.state_lock -> Supervisor._lock" in msgs \
        or "Supervisor._lock -> Task.state_lock" in msgs
    assert "self.sup.note_death" in msgs
    assert "t.poke" in msgs


def test_lockorder_waiver_on_one_edge_suppresses_whole_cycle():
    """A reviewed rationale on ANY edge breaks the ring — the sibling
    edges must not keep nagging."""
    code = CROSS_CLASS_INVERSION.format(
        waiver_a="  # analyze: ok lockorder-cycle")
    assert run_one(lockorder, [src("m.py", code)]) == []


def test_lockorder_consistent_order_clean():
    code = CROSS_CLASS_INVERSION.format(waiver_a="").replace(
        "        with self._lock:\n            t = Task()\n"
        "            t.poke()",
        "        t = Task()\n        t.poke()")
    assert run_one(lockorder, [src("m.py", code)]) == []


def test_lockorder_condition_alias_collapses_onto_lock():
    """Condition(self._lock) IS self._lock: acquiring the condition
    then the lock of another class must not split one mutex into two
    graph nodes (which would fabricate or hide cycles)."""
    code = '''
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self.b = B()

        def via_cv(self):
            with self._cv:
                self.b.touch()

        def via_lock(self):
            with self._lock:
                self.b.touch()

    class B:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def touch(self):
            with self._lock:
                self.n += 1
    '''
    f = src("m.py", code)
    edges = lockorder._collect_edges(
        [f], lockorder.conc.build_program([f]))
    assert set(edges) == {("A._lock", "B._lock")}  # ONE source node


# ---- atomicity: check-then-act (ISSUE 14) ----------------------------------


CHECK_THEN_ACT = '''
import threading

class Sup:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {{}}

    def add(self, q):
        with self._lock:
            self._pending[q] = 1

    def drop(self, q):
        with self._lock:
            has = self._pending.get(q)
        if has:{waiver}
            with self._lock:
                self._pending.pop(q)
'''


def test_atomicity_check_then_act_flagged():
    out = run_one(atomicity,
                  [src("m.py", CHECK_THEN_ACT.format(waiver=""))])
    assert rules_of(out) == {"atomicity-check-act"}
    (f,) = out
    assert "drop" in f.message and "_pending" in f.message


def test_atomicity_waiver_suppresses():
    code = CHECK_THEN_ACT.format(waiver="  # analyze: ok atomicity-check-act")
    assert run_one(atomicity, [src("m.py", code)]) == []


def test_atomicity_recheck_idiom_clean():
    """Re-acquire + re-check before acting is the check-twice idiom."""
    code = CHECK_THEN_ACT.format(waiver="").replace(
        "            with self._lock:\n                "
        "self._pending.pop(q)",
        "            with self._lock:\n                "
        "if q in self._pending:\n                    "
        "self._pending.pop(q)")
    assert run_one(atomicity, [src("m.py", code)]) == []


def test_atomicity_snapshot_return_clean():
    """Reading under the lock and only RETURNING/reporting the value
    is the snapshot idiom — no act, no finding."""
    code = CHECK_THEN_ACT.format(waiver="").replace(
        "        if has:\n            with self._lock:\n"
        "                self._pending.pop(q)",
        "        return has")
    assert run_one(atomicity, [src("m.py", code)]) == []


def test_atomicity_single_critical_section_clean():
    """Check and act inside ONE with block: nothing outlives the
    lock."""
    code = '''
    import threading

    class Sup:
        def __init__(self):
            self._lock = threading.Lock()
            self._pending = {}

        def add(self, q):
            with self._lock:
                self._pending[q] = 1

        def drop(self, q):
            with self._lock:
                has = self._pending.get(q)
                if has:
                    self._pending.pop(q)
    '''
    assert run_one(atomicity, [src("m.py", code)]) == []


# ---- waitholding (ISSUE 14) ------------------------------------------------


JOIN_UNDER_LOCK = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run)
        self._done = threading.Event()

    def _run(self):
        pass

    def stop(self):
        with self._lock:
            self._thread.join(){waiver}
'''


def test_waitholding_join_under_lock_flagged():
    out = run_one(waitholding,
                  [src("m.py", JOIN_UNDER_LOCK.format(waiver=""))])
    assert rules_of(out) == {"wait-holding"}
    (f,) = out
    assert "join()" in f.message and "Box._lock" in f.message


def test_waitholding_waiver_suppresses():
    code = JOIN_UNDER_LOCK.format(waiver="  # analyze: ok wait-holding")
    assert run_one(waitholding, [src("m.py", code)]) == []


def test_waitholding_join_outside_lock_clean():
    code = JOIN_UNDER_LOCK.format(waiver="").replace(
        "        with self._lock:\n            self._thread.join()",
        "        self._thread.join()")
    assert run_one(waitholding, [src("m.py", code)]) == []


def test_waitholding_event_wait_and_queue_put_under_lock_flagged():
    code = '''
    import queue
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._done = threading.Event()
            self._q = queue.Queue(maxsize=4)

        def bad_wait(self):
            with self._lock:
                self._done.wait()

        def bad_put(self, item):
            with self._lock:
                self._q.put(item)

        def ok_nowait(self, item):
            with self._lock:
                self._q.put_nowait(item)
    '''
    out = run_one(waitholding, [src("m.py", code)])
    assert len(out) == 2
    msgs = " | ".join(f.message for f in out)
    assert "wait()" in msgs and "put()" in msgs
    assert "ok_nowait" not in msgs


def test_waitholding_condition_idiom_exempt():
    """Waiting on the HELD condition releases it — never flagged,
    including a Condition aliased onto the held lock."""
    code = '''
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)

        def wait_directly(self):
            with self._cv:
                self._cv.wait()

        def wait_via_alias(self):
            with self._lock:
                self._cv.wait()
    '''
    assert run_one(waitholding, [src("m.py", code)]) == []


def test_waitholding_appendfront_lane_shape_recognized_and_waived():
    """Regression (ISSUE 14): the real append-front lane-lock put is
    RECOGNIZED by the pass (lock families via locktrace.lock_list /
    Lock() lists + blocking put under a family member) and suppressed
    only by its reviewed waiver — if recognition regresses, the
    waiver goes dead and this test fails."""
    with open(os.path.join(REPO, "hstream_tpu", "server",
                           "appendfront.py"), encoding="utf-8") as fh:
        text = fh.read()
    real = SourceFile("appendfront.py",
                      "hstream_tpu/server/appendfront.py", text)
    raw = waitholding.run([real], REPO)  # waivers NOT applied
    assert any(f.rule == "wait-holding"
               and "AppendFront.submit" in f.message for f in raw)
    assert run_one(waitholding, [real]) == []  # waiver suppresses


# ---- blocking --------------------------------------------------------------


def test_blocking_handler_sleep_flagged():
    code = '''
    import time

    class FooServicer:
        def Append(self, request, context):
            time.sleep(1.0)
            return request

        def helper(self, request):
            time.sleep(1.0)  # lowercase: not an RPC handler
    '''
    out = run_one(blocking, [src("m.py", code)])
    assert len(out) == 1 and out[0].rule == "blocking-hot"
    assert "time.sleep" in out[0].message


def test_blocking_unbounded_get_in_worker_loop():
    code = '''
    class W:
        def _work_loop(self):
            while True:
                item = self._q.get()
                bounded = self._q.get(timeout=0.5)
                self._stop.wait(0.1)
                d = {}.get("x")  # dict.get: not a wait
    '''
    out = run_one(blocking, [src("m.py", code)])
    assert len(out) == 1
    assert "unbounded get()" in out[0].message


def test_blocking_scrape_path_file_io():
    code = '''
    import os

    def sample(ctx):
        for _p, _d, _f in os.walk("/tmp/x"):
            pass
    '''
    out = run_one(blocking,
                  [src("hstream_tpu/stats/prometheus.py", code)])
    assert len(out) == 1 and "directory walk" in out[0].message
    # same code outside the scrape path is fine
    assert run_one(blocking, [src("hstream_tpu/other.py", code)]) == []


def test_blocking_thread_run_covered_and_bounded_ok():
    code = '''
    import threading, time

    class W(threading.Thread):
        def run(self):
            time.sleep(2)

    class Quiet(threading.Thread):
        def run(self):
            self._ev.wait(0.5)
            self._t.join(1.0)
    '''
    out = run_one(blocking, [src("m.py", code)])
    assert len(out) == 1 and "W.run" in out[0].message


def test_blocking_supervisor_backoff_sleep_carved_out():
    """ISSUE 8 carve-out: a *Supervisor class's restart thread OWNS its
    latency budget — backoff time.sleep between restart attempts is
    sanctioned. Every OTHER blocking call in the supervisor is still
    flagged, and the same sleep in a non-Supervisor worker stays hot."""
    code = '''
    import time

    class QuerySupervisor:
        def _restart_loop(self):
            while True:
                time.sleep(0.5)   # backoff between attempts: OK
                self._q.get()     # unbounded wait: still flagged

    class RetryWorker:
        def _restart_loop(self):
            time.sleep(0.5)       # no Supervisor suffix: flagged
    '''
    out = run_one(blocking, [src("m.py", code)])
    msgs = sorted(f.message for f in out)
    assert len(out) == 2, msgs
    assert "time.sleep" in msgs[0]
    assert "RetryWorker._restart_loop" in msgs[0]
    assert "unbounded get()" in msgs[1]
    assert "QuerySupervisor._restart_loop" in msgs[1]


# ---- purity ----------------------------------------------------------------


def test_purity_decorated_impure_calls():
    code = '''
    import time, random
    import jax

    @jax.jit
    def step(x):
        t = time.time()
        r = random.random()
        return x + t + r

    @jax.jit
    def pure(x):
        return x * 2
    '''
    out = run_one(purity, [src("m.py", code)])
    assert rules_of(out) == {"jax-impure"}
    assert len(out) == 2
    assert all("step" in f.message for f in out)


def test_purity_jit_by_name_and_closure_mutation():
    code = '''
    import jax

    def build():
        seen = []

        def step(x):
            seen.append(x)
            return x

        return jax.jit(step)
    '''
    out = run_one(purity, [src("m.py", code)])
    assert len(out) == 1
    assert "mutates closed-over 'seen'" in out[0].message


def test_purity_shard_map_attribute_store():
    code = '''
    import jax

    class E:
        def compile(self):
            def step(s, x):
                self.calls = 1
                return s

            self.step = jax.jit(jax.shard_map(step, mesh=None))
    '''
    out = run_one(purity, [src("m.py", code)])
    assert len(out) == 1 and "self.calls" in out[0].message


def test_purity_join_probe_kernel_shapes():
    """The interval-join kernel builders' shape — closures returning a
    decorated @jax.jit kernel from a factory — must be in the purity
    pass's scope: an impure probe/evict kernel is flagged, the clean
    twin (the real lattice.join_probe_insert / join_evict shape) is
    not."""
    bad = '''
    import time
    import jax
    import jax.numpy as jnp

    def join_probe_insert(cap, bcap, match_cap, nm, no):
        @jax.jit
        def probe_insert(mine, other, batch, n, within, cutoff):
            t = time.time()  # trace-frozen wall clock
            return mine, batch + t

        return probe_insert

    def join_evict(cap, nl, nr):
        hits = []

        @jax.jit
        def evict(left, right, cutoff, delta):
            hits.append(cutoff)  # trace-time mutation
            return left, right

        return evict
    '''
    out = run_one(purity, [src("m.py", bad)])
    assert rules_of(out) == {"jax-impure"}
    assert len(out) == 2
    assert any("probe_insert" in f.message for f in out)
    assert any("evict" in f.message for f in out)

    clean = '''
    import jax
    import jax.numpy as jnp

    def join_probe_insert(cap, bcap, match_cap, nm, no):
        @jax.jit
        def probe_insert(mine, other, batch, n, within, cutoff):
            order = jnp.argsort(batch[0])
            return mine, batch[:, order]

        return probe_insert

    def join_evict(cap, nl, nr):
        @jax.jit
        def evict(left, right, cutoff, delta):
            alive = left["ts"] >= cutoff
            return left, right, jnp.sum(alive)

        return evict
    '''
    assert run_one(purity, [src("m.py", clean)]) == []


def test_purity_donated_reuse():
    code = '''
    import numpy as np
    from hstream_tpu.engine import lattice

    class E:
        def go(self, staged):
            step = lattice.compiled_encoded_step(
                self.spec, donate_words=True)
            self.state = step(self.state, staged.words)
            return np.asarray(staged.words)  # donated!
    '''
    out = run_one(purity, [src("m.py", code)])
    assert rules_of(out) == {"jax-donated-reuse"}
    (f,) = out
    assert "staged.words" in f.message


def test_purity_donated_no_reuse_clean():
    code = '''
    from hstream_tpu.engine import lattice

    class E:
        def go(self, staged):
            step = lattice.compiled_encoded_step(
                self.spec, donate_words=True)
            self.state = step(
                self.state,
                staged.words)
            return []
    '''
    assert run_one(purity, [src("m.py", code)]) == []


# ---- errcontract -----------------------------------------------------------


ERRORS_FIXTURE = '''
import grpc

class HStreamError(Exception):
    grpc_status = grpc.StatusCode.INTERNAL

class NotFoundish(HStreamError):
    grpc_status = grpc.StatusCode.NOT_FOUND

class Exhausted(HStreamError):
    grpc_status = grpc.StatusCode.RESOURCE_EXHAUSTED
'''

HANDLERS_FIXTURE = '''
import grpc

def handler(context):
    raise NotFoundish("x")

def other(context):
    raise Exhausted("y")

def explicit(context):
    context.abort(grpc.StatusCode.FAILED_PRECONDITION, "z")
'''


def _contract_files(gateway_codes: str, retryable: str,
                    non_retryable: str):
    gw = f'''
    import grpc

    _STATUS = {{{gateway_codes}}}
    '''
    rt = f'''
    import grpc

    RETRYABLE_CODES = frozenset({{{retryable}}})
    NON_RETRYABLE_CODES = frozenset({{{non_retryable}}})
    '''
    return [
        src(errcontract.ERRORS_FILE, ERRORS_FIXTURE),
        src("hstream_tpu/server/handlers.py", HANDLERS_FIXTURE),
        src(errcontract.GATEWAY_FILE, gw),
        src(errcontract.RETRY_FILE, rt),
    ]


def test_errcontract_gaps_flagged():
    files = _contract_files(
        "grpc.StatusCode.NOT_FOUND: 404",          # missing 2 mappings
        "grpc.StatusCode.RESOURCE_EXHAUSTED, "
        "grpc.StatusCode.ABORTED",                 # ABORTED never emitted
        "grpc.StatusCode.NOT_FOUND")
    out = run_one(errcontract, files)
    by_rule = {}
    for f in out:
        by_rule.setdefault(f.rule, []).append(f.message)
    # FAILED_PRECONDITION + RESOURCE_EXHAUSTED lack HTTP mappings
    assert len(by_rule["err-http"]) == 2
    # FAILED_PRECONDITION unclassified
    assert any("FAILED_PRECONDITION" in m
               for m in by_rule["err-retry-class"])
    # ABORTED retried but never emitted
    assert any("ABORTED" in m for m in by_rule["err-dead-retry"])


def test_errcontract_complete_contract_clean():
    files = _contract_files(
        "grpc.StatusCode.NOT_FOUND: 404, "
        "grpc.StatusCode.RESOURCE_EXHAUSTED: 429, "
        "grpc.StatusCode.FAILED_PRECONDITION: 400",
        "grpc.StatusCode.RESOURCE_EXHAUSTED, "
        "grpc.StatusCode.UNAVAILABLE",             # transport: exempt
        "grpc.StatusCode.NOT_FOUND, "
        "grpc.StatusCode.FAILED_PRECONDITION")
    assert run_one(errcontract, files) == []


HINTED_ERRORS_FIXTURE = '''
import grpc

class HStreamError(Exception):
    grpc_status = grpc.StatusCode.INTERNAL

class NotLeaderish(HStreamError):
    grpc_status = grpc.StatusCode.UNAVAILABLE

    def __init__(self, message="", leader_hint=None):
        super().__init__(message)
        self.leader_hint = leader_hint
'''

HINTED_HANDLERS_FIXTURE = '''
def handler(context):
    raise NotLeaderish("fenced", leader_hint="addr")
'''


def _hinted_files(retry_body: str):
    gw = '''
    import grpc

    _STATUS = {grpc.StatusCode.UNAVAILABLE: 503,
               grpc.StatusCode.INTERNAL: 500}
    '''
    return [
        src(errcontract.ERRORS_FILE, HINTED_ERRORS_FIXTURE),
        src("hstream_tpu/server/handlers.py", HINTED_HANDLERS_FIXTURE),
        src(errcontract.GATEWAY_FILE, gw),
        src(errcontract.RETRY_FILE, retry_body),
    ]


def test_errcontract_hinted_contract_clean():
    """A hint-carrying class whose status is hinted-classified AND
    bare-non-retryable passes all three hinted rules."""
    files = _hinted_files('''
    import grpc

    RETRYABLE_CODES = frozenset()
    NON_RETRYABLE_CODES = frozenset({grpc.StatusCode.UNAVAILABLE,
                                     grpc.StatusCode.INTERNAL})
    HINTED_RETRYABLE_CODES = frozenset({grpc.StatusCode.UNAVAILABLE})
    ''')
    assert run_one(errcontract, files) == []


def test_errcontract_hinted_gaps_flagged():
    """Unclassified hint status, a dead hinted code, and a hinted code
    whose bare form escaped NON_RETRYABLE each fire their rule."""
    files = _hinted_files('''
    import grpc

    RETRYABLE_CODES = frozenset()
    NON_RETRYABLE_CODES = frozenset({grpc.StatusCode.INTERNAL})
    HINTED_RETRYABLE_CODES = frozenset({grpc.StatusCode.ABORTED})
    ''')
    out = run_one(errcontract, files)
    rules = {f.rule for f in out}
    # UNAVAILABLE (the hint class's status) is not hinted-classified
    assert "err-hinted-unclassified" in rules
    # ABORTED is hinted but no hint class emits it
    assert "err-dead-hint" in rules
    # ABORTED's bare form is not in NON_RETRYABLE_CODES
    assert "err-hinted-bare" in rules
    # the hinted check scopes to RAISED hint classes only: INTERNAL
    # (the base class, never raised) must not fire it
    assert not any("INTERNAL" in f.message for f in out
                   if f.rule == "err-hinted-unclassified")


def test_errcontract_real_tree_tables_agree():
    """Table-driven check against the LIVE modules: every status the
    server can emit has an HTTP mapping and a retryability class, and
    every retried status is emitted (or transport-generated)."""
    import grpc

    from hstream_tpu.client import retry as retry_mod
    from hstream_tpu.http_gateway import _STATUS

    files = load_tree(REPO)
    by_rel = {f.rel: f for f in files}
    classes = errcontract._error_classes(
        by_rel[errcontract.ERRORS_FILE].tree)
    emitted = set(errcontract._emitted(files, classes))
    assert "RESOURCE_EXHAUSTED" in emitted  # sanity: extraction works
    assert "NOT_FOUND" in emitted
    http = {c.name for c in _STATUS}
    retryable = {c.name for c in retry_mod.RETRYABLE_CODES}
    non_retryable = {c.name for c in retry_mod.NON_RETRYABLE_CODES}
    assert emitted <= http
    assert emitted <= (retryable | non_retryable)
    assert retryable <= emitted | errcontract.TRANSPORT_CODES
    # the classification itself is coherent
    assert not (retryable & non_retryable)
    assert grpc.StatusCode.RESOURCE_EXHAUSTED in retry_mod.RETRYABLE_CODES
    # the NOT_LEADER contract (ISSUE 9): hinted codes are an overlay on
    # non-retryable — followable only WITH a hint, never blanket-retried
    hinted = {c.name for c in retry_mod.HINTED_RETRYABLE_CODES}
    assert hinted <= non_retryable
    assert grpc.StatusCode.UNAVAILABLE in retry_mod.HINTED_RETRYABLE_CODES
    assert "UNAVAILABLE" in emitted  # NotLeaderError is raised for real


# ---- lifecycle -------------------------------------------------------------


def test_lifecycle_unjoined_thread_flagged():
    code = '''
    import threading

    class Runner:
        def start(self):
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

        def stop(self):
            self._stop.set()  # signalled but never joined
    '''
    out = run_one(lifecycle, [src("m.py", code)])
    assert len(out) == 1 and out[0].rule == "resource-leak"
    assert "_thread" in out[0].message


def test_lifecycle_unrelated_join_gives_no_credit():
    """os.path.join / a string sep.join in the same function must not
    count as teardown of an unreaped resource."""
    code = '''
    import os
    import threading

    class Runner:
        def start(self):
            self._pool = threading.Thread(target=self._run)

        def path_for(self, name):
            return os.path.join(self.root, name)
    '''
    out = run_one(lifecycle, [src("m.py", code)])
    assert len(out) == 1 and "_pool" in out[0].message


def test_lifecycle_joined_and_alias_shapes_clean():
    code = '''
    import threading
    from concurrent import futures

    class Runner:
        def start(self):
            self._thread = threading.Thread(target=self._run)
            self._pool = futures.ThreadPoolExecutor(2)
            self._workers = [threading.Thread(target=self._run)
                             for _ in range(2)]

        def stop(self):
            t = self._thread
            t.join(timeout=5)
            self._pool.shutdown(wait=True)
            for w in self._workers:
                w.join(timeout=5)
    '''
    assert run_one(lifecycle, [src("m.py", code)]) == []


# ---- registry --------------------------------------------------------------


def test_registry_unknown_metric_flagged():
    code = '''
    def f(stats, events):
        stats.stream_stat_add("no_such_metric_xyz", "s")
        events.append("no_such_kind_xyz", "msg")
    '''
    out = run_one(registry, [src("hstream_tpu/fixture.py", code)])
    unknown = [f for f in out if f.rule == "registry-unknown"]
    assert len(unknown) == 2
    assert any("no_such_metric_xyz" in f.message for f in unknown)
    assert any("no_such_kind_xyz" in f.message for f in unknown)


def test_registry_dead_entry_flagged():
    # a fixture-only tree references nothing: every registered metric
    # shows up as dead — proving direction 2 works
    out = run_one(registry, [src("hstream_tpu/fixture.py", "x = 1\n")])
    dead = [f for f in out if f.rule == "registry-dead"]
    assert any("append_total" in f.message for f in dead)


def test_registry_stage_names_cross_checked():
    """ISSUE 13 satellite: trace-span stage / kernel-family literals
    are checked against tracing.TRACE_STAGES / KERNEL_FAMILIES — a
    renamed stage silently orphans its histogram series and spans."""
    code = '''
    from hstream_tpu.common.tracing import kernel_family, trace_span

    def f(tracer, tr, stats, obs):
        with trace_span(tracer, "stepp"):        # typo'd stage
            pass
        with trace_span(tracer, "step"):         # declared: clean
            pass
        with kernel_family("probes", obs):       # typo'd family
            pass
        with kernel_family("probe", obs):        # declared: clean
            pass
        tr.record_span("q1", "emitt", trace_id="t", span_id="s",
                       t0_ms=0.0, dur_ms=1.0)    # typo'd span stage
        stats.observe("freshness_lag_ms", "ingress", 1.0)  # typo'd
        stats.observe("freshness_lag_ms", "ingest", 1.0)   # declared
        stats.observe("append_latency_ms", "anystream", 1.0)  # not a
        # stage-labeled histogram: stream labels are free-form
    '''
    out = run_one(registry, [src("hstream_tpu/fixture.py", code)])
    stage = [f for f in out if f.rule == "registry-stage"]
    assert len(stage) == 4, stage
    assert any("stepp" in f.message for f in stage)
    assert any("probes" in f.message for f in stage)
    assert any("emitt" in f.message for f in stage)
    assert any("ingress" in f.message for f in stage)


def test_registry_stage_clean_on_live_tree():
    """Every stage/family literal in the production tree is declared."""
    from tools.analyze import load_tree

    out = [f for f in registry.run(load_tree(REPO), REPO)
           if f.rule == "registry-stage"]
    assert out == [], out


def test_registry_family_call_sites_checked():
    """ISSUE 15 satellite: stat-family call sites are checked against
    the declared table (stats/families.STAT_FAMILIES) — the X-macro
    property, enforced: an undeclared family name is a finding, not a
    runtime KeyError on a cold path."""
    code = '''
    def f(stats):
        stats.stat_add("no_such_family_xyz", "s", 1.0)     # undeclared
        stats.stat_add("append_in_bytes", "s", 1.0)        # declared
        stats.stat_rate("deliverred_records", "sub")       # typo'd
        stats.stat_rate("delivered_records", "sub")        # declared
        stats.stat_ladder("emit_rows", "q1")               # declared
        stats.stat_sum("close_cycle", "q1")                # typo'd
    '''
    out = run_one(registry, [src("hstream_tpu/fixture.py", code)])
    fam = [f for f in out if f.rule == "registry-family"]
    assert len(fam) == 3, fam
    assert any("no_such_family_xyz" in f.message for f in fam)
    assert any("deliverred_records" in f.message for f in fam)
    assert any("close_cycle" in f.message for f in fam)
    # declared families never misreport under the legacy rule either
    assert not any("append_in_bytes" in f.message for f in out
                   if f.rule in ("registry-family", "registry-unknown"))


def test_registry_family_dead_entry_flagged():
    """Direction 2 covers the family table too: a declared family no
    call site feeds is a dead registry entry."""
    out = run_one(registry, [src("hstream_tpu/fixture.py", "x = 1\n")])
    dead = [f for f in out if f.rule == "registry-dead"]
    assert any("delivered_records" in f.message for f in dead)
    assert any("emit_rows" in f.message for f in dead)


def test_registry_family_clean_on_live_tree():
    """Every stat-family literal in the production tree names a
    declared family, and every declared family has a live call site."""
    from tools.analyze import load_tree

    out = [f for f in registry.run(load_tree(REPO), REPO)
           if f.rule == "registry-family"
           or (f.rule == "registry-dead"
               and "time_series" in f.message)]
    assert out == [], out


# ---- dispatch (ISSUE 7) ----------------------------------------------------


HOT = "hstream_tpu/engine/executor.py"  # a dispatch-sync hot-path rel


def test_dispatch_fetch_in_loop_blows_budget():
    """The canonical regression: a fetch per window inside a contract
    function — the exact shape the fused close exists to prevent."""
    code = '''
    import numpy as np
    from hstream_tpu.engine import lattice

    class Ex:
        def _compile(self):
            fns = lattice.compiled(self.spec)
            self._extract_touched = fns.extract_touched

        # contract: dispatches<=1 fetches<=1
        def drain(self):
            state, packed = self._extract_touched(self.state)
            out = []
            for w in self.windows:
                out.append(np.asarray(packed[w]))
            return out
    '''
    out = run_one(dispatch, [src("m.py", code)])
    assert rules_of(out) == {"dispatch-budget"}
    (f,) = out
    assert "loop" in f.message and "self.windows" in f.message


def test_dispatch_static_count_exceeds_budget():
    code = '''
    import numpy as np
    from hstream_tpu.engine import lattice

    class Ex:
        def _compile(self):
            fns = lattice.compiled(self.spec)
            self._extract_touched = fns.extract_touched

        # contract: dispatches<=1 fetches<=1
        def close(self):
            s1 = self._extract_touched(self.state)
            s2 = self._extract_touched(self.state)
            return np.asarray(s1), np.asarray(s2)
    '''
    out = run_one(dispatch, [src("m.py", code)])
    assert len(out) == 2  # dispatches AND fetches exceeded
    assert all(f.rule == "dispatch-budget" for f in out)
    assert any("dispatch site(s)" in f.message for f in out)
    assert any("fetch site(s)" in f.message for f in out)


def test_dispatch_shape_group_stacking_and_branches_clean():
    """The repo's real drain shape — early-return branches take the
    max, the by_shape stacking loop is the sanctioned ONE-fetch-per-
    compiled-shape idiom — fits dispatches<=1 fetches<=1."""
    code = '''
    import jax.numpy as jnp
    import numpy as np

    class Ex:
        # contract: dispatches<=0 fetches<=1
        def drain_closed(self):
            if not self._pending:
                return []
            if len(self._pending) == 1:
                return np.asarray(self._pending[0])
            by_shape = {}
            for starts, packed in self._pending:
                by_shape.setdefault(packed.shape, []).append(packed)
            out = []
            for group in by_shape.values():
                out.append(np.asarray(jnp.stack(group)))
            return out
    '''
    assert run_one(dispatch, [src("m.py", code)]) == []


def test_dispatch_append_path_is_policed():
    """ISSUE 12: the framed append path is registered hot — it is
    host-only BY CONTRACT (dispatches<=0 fetches<=0), so a device sync
    creeping into the ingress door is flagged, bare or budgeted."""
    bare = '''
    import numpy as np

    class AppendFront:
        def submit(self, logid, payloads):
            return np.asarray(self.state)
    '''
    out = run_one(dispatch,
                  [src("hstream_tpu/server/appendfront.py", bare)])
    assert len(out) == 1 and out[0].rule == "dispatch-sync"
    budgeted = bare.replace(
        "        def submit(self, logid, payloads):",
        "        # contract: dispatches<=0 fetches<=0\n"
        "        def submit(self, logid, payloads):")
    out = run_one(dispatch,
                  [src("hstream_tpu/common/colframe.py", budgeted)])
    assert len(out) == 1 and out[0].rule == "dispatch-budget"


def test_dispatch_sync_in_hot_path_flagged_and_contract_exempts():
    bare = '''
    import numpy as np

    class Ex:
        def hot(self):
            return np.asarray(self.state["count"])
    '''
    out = run_one(dispatch, [src(HOT, bare)])
    assert len(out) == 1 and out[0].rule == "dispatch-sync"
    # the same sync under a declared budget is sanctioned + checked
    annotated = bare.replace("        def hot(self):",
                             "        # contract: fetches<=1\n"
                             "        def hot(self):")
    assert run_one(dispatch, [src(HOT, annotated)]) == []
    # and outside the kernel/executor layer it is not policed
    assert run_one(dispatch, [src("hstream_tpu/server/x.py", bare)]) \
        == []


def test_dispatch_host_typed_asarray_not_a_fetch():
    code = '''
    import numpy as np

    class Ex:
        def ingest(self, ts_ms):
            return np.asarray(ts_ms, dtype=np.int64)
    '''
    assert run_one(dispatch, [src(HOT, code)]) == []


SESSION_HOT = "hstream_tpu/engine/session.py"  # ISSUE 10 hot-path rel


def test_dispatch_session_kernels_are_dispatch_sites():
    """The session kernel factories count as dispatches: a second step
    dispatch (or a per-cycle fetch loop) inside a session contract
    function blows the budget — the shape the fused session step
    exists to prevent."""
    code = '''
    import numpy as np
    from hstream_tpu.engine import lattice

    class SessionExecutor:
        # contract: dispatches<=1 fetches<=0
        def _process_device(self, packed):
            step = lattice.session_step_kernel(
                self.spec, self.schema, self.layout, 512, 4096)
            a = step(self.arena, packed)
            b = step(a, packed)      # second dispatch: budget blown
            return b
    '''
    out = run_one(dispatch, [src(SESSION_HOT, code)])
    assert len(out) == 1 and out[0].rule == "dispatch-budget"
    assert "dispatch site(s)" in out[0].message


def test_dispatch_session_extract_fetch_loop_flagged():
    """A fetch per pending close cycle inside drain_closed — the
    stacked pow2 drain exists to prevent exactly this."""
    code = '''
    import numpy as np
    from hstream_tpu.engine import lattice

    class SessionExecutor:
        # contract: dispatches<=0 fetches<=1
        def drain_closed(self):
            out = []
            for codes, packed in self._pending:
                out.append(np.asarray(packed))
            return out
    '''
    out = run_one(dispatch, [src(SESSION_HOT, code)])
    assert rules_of(out) == {"dispatch-budget"}
    assert "loop" in out[0].message


def test_dispatch_session_unannotated_sync_flagged():
    """session.py is a dispatch-sync hot-path file now: a bare device
    sync without a contract budget is a hot-path regression."""
    code = '''
    import numpy as np

    class SessionExecutor:
        def _peek_device(self):
            return np.asarray(self._dev["arena"]["code"])
    '''
    out = run_one(dispatch, [src(SESSION_HOT, code)])
    assert len(out) == 1 and out[0].rule == "dispatch-sync"


def test_dispatch_contract_syntax_error_flagged():
    code = '''
    class Ex:
        # contract: dispatch<=1
        def f(self):
            return 1
    '''
    out = run_one(dispatch, [src("m.py", code)])
    assert len(out) == 1 and out[0].rule == "dispatch-contract-syntax"


def test_dispatch_waiver_suppresses():
    code = '''
    import numpy as np

    class Ex:
        def hot(self):
            # analyze: ok dispatch-sync — test waiver
            return np.asarray(self.state["count"])
    '''
    assert run_one(dispatch, [src(HOT, code)]) == []


# ---- retrace (ISSUE 7) -----------------------------------------------------


def test_retrace_uncached_jit_flagged():
    code = '''
    import jax

    class Ex:
        def step_batch(self, batch):
            f = jax.jit(self._step)      # fresh wrapper per call!
            return f(batch)
    '''
    out = run_one(retrace, [src("m.py", code)])
    assert len(out) == 1 and out[0].rule == "retrace-uncached-jit"
    assert "step_batch" in out[0].message


def test_retrace_factory_shapes_sanctioned():
    code = '''
    import functools

    import jax

    @functools.lru_cache(maxsize=64)
    def compiled_step(cap):
        @jax.jit
        def step(state, batch):
            return state

        return step

    def build_extract(spec):
        return jax.jit(lambda s: s)

    @jax.jit
    def rebase(state, delta):
        return state
    '''
    assert run_one(retrace, [src("m.py", code)]) == []


def test_retrace_traced_branch_flagged_none_test_exempt():
    bad = '''
    import jax

    @jax.jit
    def step(x, n):
        if n > 0:
            return x + n
        return x
    '''
    out = run_one(retrace, [src("m.py", bad)])
    assert len(out) == 1 and out[0].rule == "retrace-traced-branch"
    assert "'n'" in out[0].message

    ok = '''
    import jax

    @jax.jit
    def step(x, mask=None):
        if mask is None:
            return x
        return x * mask
    '''
    assert run_one(retrace, [src("m.py", ok)]) == []


def test_retrace_float_static_arg_flagged():
    code = '''
    import jax

    def step(state, rate=0.5):
        return state * rate

    compiled = jax.jit(step, static_argnums=(1,))
    '''
    out = run_one(retrace, [src("m.py", code)])
    assert len(out) == 1 and out[0].rule == "retrace-static-arg"
    assert "rate" in out[0].message


def test_retrace_raw_len_shape_key_flagged():
    bad = '''
    from hstream_tpu.engine import lattice

    def probe(batch, dev):
        kern = lattice.join_probe_insert(
            dev["cap"], len(batch), dev["match_cap"], 2, 2)
        return kern
    '''
    out = run_one(retrace, [src("m.py", bad)])
    assert len(out) == 1 and out[0].rule == "retrace-shape-key"
    ok = bad.replace("len(batch)", "bcap")
    assert run_one(retrace, [src("m.py", ok)]) == []


def test_retrace_session_factory_raw_len_shape_key_flagged():
    """The session kernel factories key their compile cache on the
    pow2-padded batch/segment capacity; a raw len() defeats it —
    one XLA executable per distinct batch size (ISSUE 10)."""
    bad = '''
    from hstream_tpu.engine import lattice

    def step(dev, schema, batch, packed):
        kern = lattice.session_step_kernel(
            dev["spec"], schema, dev["layout"], dev["cap"], len(batch))
        return kern(dev["arena"], packed)
    '''
    out = run_one(retrace, [src("m.py", bad)])
    assert len(out) == 1 and out[0].rule == "retrace-shape-key"
    ok = bad.replace("len(batch)", "bcap")
    assert run_one(retrace, [src("m.py", ok)]) == []
    # the merge-mode factory is covered too
    bad2 = bad.replace("session_step_kernel(\n"
                       "            dev[\"spec\"], schema, "
                       "dev[\"layout\"], dev[\"cap\"], len(batch))",
                       "session_merge_kernel(\n"
                       "            dev[\"spec\"], dev[\"cap\"], "
                       "len(batch))")
    out2 = run_one(retrace, [src("m.py", bad2)])
    assert len(out2) == 1 and out2[0].rule == "retrace-shape-key"


# ---- overflow (ISSUE 7) ----------------------------------------------------


def test_overflow_arith_on_int32_cast_ts():
    """The seeded 'raw int32 ts arithmetic' violation: narrowing
    BEFORE subtracting wraps before any guard can fire."""
    code = '''
    import numpy as np

    class Ex:
        def ingest(self, ts_ms):
            rel = np.asarray(ts_ms).astype(np.int32) - self.epoch
            return rel
    '''
    out = run_one(overflow, [src("m.py", code)])
    assert rules_of(out) == {"overflow-ts-arith"}


def test_overflow_unguarded_narrow_flagged_guarded_clean():
    bad = '''
    import numpy as np

    class Ex:
        def wm(self):
            return np.int32(self.watermark_abs - self.epoch)
    '''
    out = run_one(overflow, [src("m.py", bad)])
    assert rules_of(out) == {"overflow-narrowing"}

    guarded = '''
    import numpy as np

    class Ex:
        def wm(self):
            rel = self.watermark_abs - self.epoch
            if rel >= (1 << 31):
                raise OverflowError("span")
            return np.int32(rel)
    '''
    assert run_one(overflow, [src("m.py", guarded)]) == []


def test_overflow_rebase_call_counts_as_guard():
    code = '''
    import numpy as np

    class Ex:
        def ingest(self, bts):
            self._maybe_rebase(int(bts.min()), int(bts.max()))
            return (bts - self.t0).astype(np.int32)
    '''
    assert run_one(overflow, [src("m.py", code)]) == []


def test_overflow_device_code_exempt():
    """Jitted kernels (and helpers they call) compute in the rebased
    int32 space by design — the host guards the boundary."""
    code = '''
    import jax
    import jax.numpy as jnp

    def pack_rows(count, win_start):
        return jnp.broadcast_to(jnp.asarray(win_start, jnp.int32),
                                count.shape)

    def build_extract(spec):
        @jax.jit
        def extract(state, slot):
            ts32 = state["ts"].astype(jnp.int32)
            return pack_rows(state["count"], ts32)

        return extract
    '''
    assert run_one(overflow, [src("m.py", code)]) == []


def test_overflow_non_time_names_not_matched():
    code = '''
    import numpy as np

    def shape_stats(counts):
        return counts.astype(np.int32)
    '''
    assert run_one(overflow, [src("m.py", code)]) == []


# ---- shardmap (ISSUE 7) ----------------------------------------------------


SHARD_CLEAN = '''
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

def build(mesh, data_axis="data"):
    def merged(state):
        return jax.lax.psum(state, data_axis)

    def step_local(state, batch):
        shard = jax.lax.axis_index(data_axis)
        return merged(state) + shard

    return jax.jit(jax.shard_map(step_local, mesh=mesh))
'''


def test_shardmap_clean_bodies_pass():
    assert run_one(shardmap, [src("m.py", SHARD_CLEAN)]) == []


def test_shardmap_callback_in_body_flagged():
    """The seeded callback-in-shard_map violation."""
    code = SHARD_CLEAN.replace(
        "        shard = jax.lax.axis_index(data_axis)",
        "        shard = jax.lax.axis_index(data_axis)\n"
        "        jax.debug.print(\"shard {s}\", s=shard)")
    out = run_one(shardmap, [src("m.py", code)])
    assert rules_of(out) == {"shardmap-callback"}
    assert "jax.debug.print" in out[0].message


def test_shardmap_host_fetch_in_body_flagged():
    code = SHARD_CLEAN.replace(
        "        return merged(state) + shard",
        "        import numpy as np\n"
        "        return np.asarray(merged(state)) + shard")
    out = run_one(shardmap, [src("m.py", code)])
    assert rules_of(out) == {"shardmap-callback"}
    assert "np.asarray" in out[0].message


def test_shardmap_collective_outside_body_flagged():
    code = '''
    import jax

    def merge_on_host(partials):
        return jax.lax.psum(partials, "data")
    '''
    out = run_one(shardmap, [src("m.py", code)])
    assert rules_of(out) == {"shardmap-collective"}


def test_shardmap_axis_typo_flagged():
    code = '''
    import jax
    from jax.sharding import Mesh

    def build(devices):
        mesh = Mesh(devices, ("data", "key"))

        def step_local(state):
            return jax.lax.psum(state, "dta")

        return jax.shard_map(step_local, mesh=mesh)
    '''
    out = run_one(shardmap, [src("m.py", code)])
    assert "shardmap-axis" in rules_of(out)
    (f,) = [f for f in out if f.rule == "shardmap-axis"]
    assert "'dta'" in f.message and "data" in f.message


SESSION_SHARDED = '''
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

def build(mesh, key_axis="key"):
    def step_local(arena, packed):
        shard = jax.lax.axis_index(key_axis)
        owned = (packed[0] % 8) == shard
        packed = packed.at[2].set(
            jnp.where(owned, packed[2], packed[2] & ~1))
        return arena, packed

    return jax.jit(jax.shard_map(step_local, mesh=mesh))
'''


def test_shardmap_session_ownership_mask_clean():
    """ISSUE 16 shape: the sharded session arena's ownership masking
    (axis_index inside the body, ZERO collectives) must pass clean —
    axis_index is a mesh-bound primitive, legal only under shard_map,
    and the session lattice keeps it there."""
    assert run_one(shardmap, [src("m.py", SESSION_SHARDED)]) == []


def test_shardmap_join_concat_gather_clean():
    """ISSUE 16 shape: the sharded join's ICI concat point — tiled
    all_gather of per-shard match buffers along the key axis inside
    the shard_map body — is mesh-legal and must not be flagged."""
    code = '''
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    def build(mesh, key_axis="key"):
        def probe_local(store, batch):
            shard = jax.lax.axis_index(key_axis)
            kid = batch[2] * 0 + shard
            kid = jax.lax.all_gather(kid, key_axis, tiled=True)
            return store, kid

        return jax.jit(jax.shard_map(probe_local, mesh=mesh))
    '''
    assert run_one(shardmap, [src("m.py", code)]) == []


def test_shardmap_session_gather_outside_body_flagged():
    """The inverse pin: an all_gather in a helper NEVER wrapped by
    shard_map (e.g. a session drain trying to concat host-side) is the
    unbound-axis trap the pass exists for."""
    code = '''
    import jax

    def drain_concat(parts):
        return jax.lax.all_gather(parts, "key", tiled=True)
    '''
    out = run_one(shardmap, [src("m.py", code)])
    assert rules_of(out) == {"shardmap-collective"}


def test_shardmap_session_callback_in_body_flagged():
    """A host fetch inside the session step body (per-shard sync —
    would serialize the mesh) keeps tripping shardmap-callback."""
    code = SESSION_SHARDED.replace(
        "        return arena, packed",
        "        import numpy as np\n"
        "        return arena, np.asarray(packed)")
    out = run_one(shardmap, [src("m.py", code)])
    assert rules_of(out) == {"shardmap-callback"}


# ---- analyze CLI --json ----------------------------------------------------


def test_cli_json_output(tmp_path):
    """--json emits one machine-readable array of the NEW findings."""
    mini = tmp_path / "mini"
    (mini / "hstream_tpu").mkdir(parents=True)
    (mini / "tools").mkdir()
    (mini / "hstream_tpu" / "box.py").write_text(
        textwrap.dedent(LOCKED_CLASS.format(waiver="")))
    (mini / "bench.py").write_text("")
    base = str(tmp_path / "b.json")
    r = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--only", "locks",
         "--repo", str(mini), "--baseline", base, "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    records = json.loads(r.stdout)
    assert len(records) == 1
    rec = records[0]
    assert rec["rule"] == "lock-guard"
    assert rec["pass"] == "locks"  # owning pass per record (ISSUE 14)
    assert rec["path"] == "hstream_tpu/box.py"
    assert isinstance(rec["line"], int) and rec["line"] > 0
    assert "_val" in rec["message"]
    # a clean tree emits an empty array and exits 0
    (mini / "hstream_tpu" / "box.py").write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--only", "locks",
         "--repo", str(mini), "--baseline", base, "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0 and json.loads(r.stdout) == []


def test_cli_json_stable_order_and_pass_names(tmp_path):
    """--json output is a total order over (path, line, rule, message)
    and every record names its owning pass — CI annotators must not
    have to re-sort or re-derive the rule->pass mapping (ISSUE 14)."""
    from tools.analyze import all_passes, rule_passes

    owners = rule_passes()
    for name, mod in all_passes().items():
        for rid in mod.RULES:
            assert owners[rid] == name
    mini = tmp_path / "mini"
    (mini / "hstream_tpu").mkdir(parents=True)
    (mini / "tools").mkdir()
    (mini / "bench.py").write_text("")
    # two findings from two passes in one file: locks + waitholding
    (mini / "hstream_tpu" / "box.py").write_text(textwrap.dedent('''
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._thread = threading.Thread(target=self.bump)

        def bump(self):
            with self._lock:
                self._val += 1

        def reset(self):
            with self._lock:
                self._val = 0

        def peek(self):
            return self._val

        def stop(self):
            with self._lock:
                self._thread.join()
    '''))
    base = str(tmp_path / "b.json")
    r = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--repo", str(mini),
         "--baseline", base, "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    records = json.loads(r.stdout)
    assert len(records) >= 2
    keys = [(x["path"], x["line"], x["rule"], x["message"])
            for x in records]
    assert keys == sorted(keys)
    by_rule = {x["rule"]: x["pass"] for x in records}
    assert by_rule.get("lock-guard") == "locks"
    assert by_rule.get("wait-holding") == "waitholding"


# ---- RetraceGuard: runtime recompile contract (ISSUE 7) --------------------


@pytest.fixture
def retrace_guard():
    """Context factory asserting ZERO XLA compiles inside the block —
    the runtime complement of the static retrace pass."""
    import contextlib

    from hstream_tpu.common.tracing import RetraceGuard

    @contextlib.contextmanager
    def guard_zero():
        with RetraceGuard() as g:
            yield g
        assert g.count == 0, \
            f"steady state compiled {g.count} new XLA executable(s)"

    return guard_zero


def test_retrace_guard_counts_first_compile():
    import jax
    import jax.numpy as jnp

    from hstream_tpu.common.tracing import RetraceGuard

    f = jax.jit(lambda x: x * 3 + 1)
    with RetraceGuard() as g:
        f(jnp.zeros(5))
    assert g.count >= 1  # fresh wrapper: at least its own compile
    with RetraceGuard() as g2:
        f(jnp.zeros(5))
    assert g2.count == 0  # cached executable: no recompile


def test_retrace_guard_zero_steady_state_fused_close(retrace_guard):
    """50 post-warmup fused-close batches compile NOTHING (the
    acceptance contract; same config the CI smoke gate runs)."""
    import bench

    ex, feed, warm = bench._smoke_tumbling_config()
    for i in range(warm):
        feed(i)
    ex.block_until_ready()
    with retrace_guard():
        for i in range(warm, warm + 50):
            feed(i)
        ex.block_until_ready()


def test_retrace_guard_zero_steady_state_device_session(retrace_guard):
    """50 post-warmup device-session micro-batches (steps, close
    extracts, stacked deferred drains) compile NOTHING (ISSUE 10)."""
    import bench

    ex, feed, warm = bench._smoke_session_config()
    for b in range(warm):
        feed(b)
    ex.flush_changes()
    ex.block_until_ready()
    assert ex._dev is not None, "device sessions did not activate"
    with retrace_guard():
        for b in range(warm, warm + 50):
            feed(b)
        ex.flush_changes()
        ex.block_until_ready()
    st = ex.session_stats
    assert st["step_dispatches"] == st["batches"]


def test_retrace_guard_zero_steady_state_device_join(retrace_guard):
    """50 post-warmup device-join micro-batches compile NOTHING."""
    import bench

    ex, feed, warm = bench._smoke_join_config()
    for b in range(warm):
        feed(b)
    ex.flush_changes()
    ex.block_until_ready()
    assert ex._dev is not None, "device join did not activate"
    with retrace_guard():
        for b in range(warm, warm + 50):
            feed(b)
        ex.flush_changes()
        ex.block_until_ready()


def test_kernel_recompiles_counter_taps_compiles():
    import jax
    import jax.numpy as jnp

    from hstream_tpu.common.tracing import install_recompile_counter
    from hstream_tpu.stats import StatsHolder

    stats = StatsHolder()
    install_recompile_counter(stats, stream="_test")
    jax.jit(lambda x: x - 7)(jnp.zeros(3))
    assert stats.stream_stat_get("kernel_recompiles", "_test") >= 1


def test_named_guard_attributes_recompiles_to_stream():
    """ISSUE 13 satellite: a compile observed while a NAMED guard is
    active counts against that stream, not the sink's default
    pseudo-stream — per-query recompile evidence used to collapse
    into `_process` unrecoverably."""
    import jax
    import jax.numpy as jnp

    from hstream_tpu.common.tracing import (
        RetraceGuard,
        install_recompile_counter,
    )
    from hstream_tpu.stats import StatsHolder

    stats = StatsHolder()
    install_recompile_counter(stats, stream="_namedtest")
    with RetraceGuard(name="q-attr-1") as g:
        jax.jit(lambda x: x * 3 + 11)(jnp.zeros(5))
    assert g.count >= 1
    named = stats.stream_stat_get("kernel_recompiles", "q-attr-1")
    assert named >= 1
    # the default sink stream saw NONE of the named-guard compiles
    assert stats.stream_stat_get("kernel_recompiles",
                                 "_namedtest") == 0
    # with no named guard active, attribution falls back to the
    # sink's stream as before
    jax.jit(lambda x: x * 5 + 13)(jnp.zeros(5))
    assert stats.stream_stat_get("kernel_recompiles",
                                 "_namedtest") >= 1
    assert stats.stream_stat_get("kernel_recompiles",
                                 "q-attr-1") == named


def test_compile_family_attribution_via_kernel_family():
    """A compile triggered inside a kernel_family scope lands in the
    factory_recompiles counter under that family."""
    import jax
    import jax.numpy as jnp

    from hstream_tpu.common.tracing import (
        install_recompile_counter,
        kernel_family,
    )
    from hstream_tpu.stats import StatsHolder

    stats = StatsHolder()
    install_recompile_counter(stats, stream="_famtest")
    seen = []
    with kernel_family("probe", lambda fam, s: seen.append((fam, s))):
        jax.jit(lambda x: x - 21)(jnp.zeros(7))
    assert stats.stream_stat_get("factory_recompiles", "probe") >= 1
    assert seen and seen[0][0] == "probe" and seen[0][1] >= 0.0


# ---- waivers / baseline / framework ----------------------------------------


def test_waiver_on_preceding_comment_line():
    code = LOCKED_CLASS.format(waiver="").replace(
        "        return self._val",
        "        # analyze: ok lock-guard\n        return self._val")
    assert run_one(locks, [src("m.py", code)]) == []


def test_waiver_bare_ok_covers_all_rules():
    code = LOCKED_CLASS.format(waiver="  # analyze: ok")
    assert run_one(locks, [src("m.py", code)]) == []


def test_baseline_roundtrip_suppresses(tmp_path):
    f = Finding("lock-guard", "m.py", 17, "unguarded read of '_val'")
    path = str(tmp_path / "baseline.json")
    write_baseline([f], path)
    base = load_baseline(path)
    assert f.key() in base
    # line drift does not un-baseline a finding
    drifted = Finding("lock-guard", "m.py", 99, f.message)
    assert drifted.key() in base
    # a different message is a NEW finding
    other = Finding("lock-guard", "m.py", 17, "unguarded read of '_x'")
    assert other.key() not in base


def test_cli_baseline_gate(tmp_path):
    """End-to-end: a seeded violation fails the CLI, gets baselined,
    then passes; a waiver also clears it."""
    mini = tmp_path / "mini"
    (mini / "hstream_tpu").mkdir(parents=True)
    (mini / "tools").mkdir()
    bad = textwrap.dedent(LOCKED_CLASS.format(waiver=""))
    (mini / "hstream_tpu" / "box.py").write_text(bad)
    (mini / "bench.py").write_text("")
    base = str(tmp_path / "b.json")

    def cli(*extra):
        return subprocess.run(
            [sys.executable, "-m", "tools.analyze", "--only", "locks",
             "--repo", str(mini), "--baseline", base, *extra],
            capture_output=True, text=True, cwd=REPO)

    r = cli()
    assert r.returncode == 1 and "lock-guard" in r.stdout
    assert "rule docs" in r.stdout  # failure prints the fired docs
    r = cli("--write-baseline")
    assert r.returncode == 0
    r = cli()
    assert r.returncode == 0 and "baselined" in r.stdout
    # stats mode emits per-rule counts
    r = cli("--stats")
    assert "lock-guard" in r.stdout and r.returncode == 0


def test_write_baseline_with_only_preserves_other_passes(tmp_path):
    """`--only X --write-baseline` must not drop baseline entries owned
    by the passes that did not run."""
    from tools.analyze import BASELINE_PATH  # noqa: F401 — docs anchor

    path = str(tmp_path / "b.json")
    kept = Finding("resource-leak", "a.py", 3, "leaked thread")
    write_baseline([kept], path)
    # rewrite for the locks pass only: resource-leak entries survive
    new = Finding("lock-guard", "b.py", 9, "unguarded read of '_x'")
    write_baseline([new], path, keep_rules={"resource-leak"})
    base = load_baseline(path)
    assert kept.key() in base and new.key() in base
    # a full rewrite (no keep_rules) replaces everything
    write_baseline([new], path)
    base = load_baseline(path)
    assert kept.key() not in base and new.key() in base


# ---- casdiscipline (ISSUE 19) ---------------------------------------------


def test_cas_blind_write_on_protocol_key_flagged():
    code = '''
    def publish(store, node):
        store.meta_put("cluster/nodes/" + node, b"{}")
        store.meta_put("scheduler/query/q1", b"{}")
        store.meta_delete("vcs/flow/limits")
        store.meta_put(META_EPOCH, b"3")
    '''
    out = run_one(casdiscipline, [src("m.py", code)])
    assert rules_of(out) == {"cas-blind-meta-write"}
    assert len(out) == 4


def test_cas_blind_write_ignores_data_plane_and_dynamic_keys():
    code = '''
    def ok(store, e, key):
        store.meta_put("snapshots/q1/0", b"...")   # data plane
        store.meta_put(e.meta_key, e.meta_value)   # replication apply
        store.meta_put(key, b"x")                  # dynamic
        store.meta_cas("scheduler/query/q1", None, b"{}")  # the idiom
    '''
    assert run_one(casdiscipline, [src("m.py", code)]) == []


def test_cas_blind_write_waiver_suppresses():
    code = '''
    def stamp(store):
        # analyze: ok cas-blind-meta-write
        store.meta_put("replica/node_id", b"n1")
    '''
    assert run_one(casdiscipline, [src("m.py", code)]) == []


def test_cas_put_version_from_same_function_get_is_clean():
    code = '''
    def claim(ctx, key, value):
        for _ in range(16):
            cur = ctx.config.get(key)
            try:
                ctx.config.put(key, value,
                               base_version=None if cur is None else cur[0])
                return
            except VersionMismatch:
                continue

    def bump(ctx):
        cur = ctx.config.get("cluster/boot_epoch")
        version, raw = cur
        ctx.config.put("cluster/boot_epoch", b"2", base_version=version)
        ctx.config.delete("cluster/boot_epoch", base_version=cur[0])
    '''
    assert run_one(casdiscipline, [src("m.py", code)]) == []


def test_cas_put_foreign_version_flagged():
    code = '''
    def overwrite(ctx, key, value, cached_version):
        ctx.config.put(key, value, base_version=cached_version)

    def constant(ctx, key, value):
        ctx.config.put(key, value, base_version=3)

    def stale(ctx, key):
        ctx.config.delete(key, base_version=ctx.last_seen)
    '''
    out = run_one(casdiscipline, [src("m.py", code)])
    assert rules_of(out) == {"cas-put-foreign-version"}
    assert len(out) == 3
    assert any("cached_version" in f.message for f in out)
    assert any("constant version" in f.message for f in out)


def test_cas_epoch_nonmonotone_flagged_and_guard_clears():
    # module mentions load_epoch -> the replication epoch plane
    code = '''
    from store import load_epoch

    class F:
        def promote(self, epoch):
            self._epoch = epoch          # no guard in scope

        def accept(self, request):
            if request.epoch > self._epoch:
                self._epoch = int(request.epoch)

        def boot(self, local):
            self._epoch = load_epoch(local)

        def bump(self):
            self._epoch = self._epoch + 1
    '''
    out = run_one(casdiscipline, [src("m.py", code)])
    assert rules_of(out) == {"cas-epoch-nonmonotone"}
    (f,) = out
    assert "promote" in f.message


def test_cas_epoch_rule_skips_engine_time_epochs():
    # no load_epoch/boot_epoch/META_EPOCH in the module: `epoch` here
    # is the executor's timestamp base, not a fencing token
    code = '''
    class Executor:
        def _rebase(self, min_ts, back):
            self.epoch = min_ts - back
    '''
    assert run_one(casdiscipline, [src("m.py", code)]) == []


def test_cas_lease_raw_interval_comparison_flagged():
    code = '''
    def live(record, now_ms, interval_ms, lease_ms):
        age = now_ms - record["hb_ms"]
        if age <= 3 * interval_ms:       # re-derives the bound: BUG
            return True
        return age <= lease_ms           # the clamped lease: fine
    '''
    out = run_one(casdiscipline, [src("m.py", code)])
    assert rules_of(out) == {"cas-lease-raw"}
    assert len(out) == 1


def test_casdiscipline_live_tree_only_carries_reviewed_waivers():
    """Triage verdict, pinned: the production tree is CLEAN after
    waivers, and the waivers are LOAD-BEARING — stripping the
    follower-plane waivers in store/replica.py re-exposes exactly the
    reviewed findings (9 blind single-writer meta writes + 1
    caller-guarded epoch assignment). A stale waiver or a new
    violation both break this test."""
    files = load_tree(REPO)
    assert run_one(casdiscipline, files) == []
    replica = next(f for f in files
                   if f.rel == "hstream_tpu/store/replica.py")
    raw = [f for f in casdiscipline.run(files, REPO)
           if f.path == replica.rel]
    blind = [f for f in raw if f.rule == "cas-blind-meta-write"]
    epoch = [f for f in raw if f.rule == "cas-epoch-nonmonotone"]
    assert len(blind) == 9, blind
    assert len(epoch) == 1, epoch
    for f in raw:  # every one is suppressed by a reviewed waiver
        assert replica.waived(f.line, f.rule), f


# ---- timeunit (ISSUE 19) ---------------------------------------------------


def test_timeunit_mix_flagged():
    code = '''
    import time

    def deadline(now_ms, timeout_s):
        return now_ms + timeout_s            # 1000x off

    def age(start_ms):
        return time.time() - start_ms        # seconds minus ms

    def expired(hb_ms, lease_timeout_s):
        if hb_ms > time.monotonic():
            return True
        return hb_ms - lease_timeout_s > 0
    '''
    out = run_one(timeunit, [src("m.py", code)])
    assert rules_of(out) == {"timeunit-mix"}
    assert len(out) == 4


def test_timeunit_conversion_factor_clears():
    code = '''
    import time

    def ok(now_ms, timeout_s, dur_ms):
        a = now_ms + timeout_s * 1000
        b = time.time() * 1e3 - dur_ms
        c = now_ms * 0.001 - timeout_s
        d = int(time.time() * 1000) - dur_ms
        return a, b, c, d
    '''
    assert run_one(timeunit, [src("m.py", code)]) == []


def test_timeunit_ignores_non_time_identifiers():
    code = '''
    def ok(stats, args, items, vals):
        total = stats + args                 # trailing s != seconds
        if items > vals:
            return total
        ms = 5
        return ms + 3                        # same-unit arithmetic
    '''
    assert run_one(timeunit, [src("m.py", code)]) == []


def test_timeunit_waiver_suppresses():
    code = '''
    def f(now_ms, timeout_s):
        return now_ms + timeout_s  # analyze: ok timeunit-mix
    '''
    assert run_one(timeunit, [src("m.py", code)]) == []


def test_timeunit_live_tree_clean():
    assert run_one(timeunit, load_tree(REPO)) == []


# ---- waiver-dead (stale-waiver audit, ISSUE 19) ----------------------------


def test_dead_waiver_flagged_live_waiver_not():
    code = '''
    def f(now_ms, timeout_s, x_ms, y_ms):
        a = now_ms + timeout_s  # analyze: ok timeunit-mix
        b = x_ms + y_ms         # analyze: ok timeunit-mix
        return a + b
    '''
    out, _rules = run_passes([src("m.py", code)], only=["timeunit"])
    assert rules_of(out) == {"waiver-dead"}
    (f,) = out
    assert f.line == 4  # the same-unit line: its waiver excuses nothing
    assert "timeunit-mix" in f.message


def test_dead_waiver_scoped_to_selected_passes():
    code = '''
    def f():
        return 1  # analyze: ok lock-guard
    '''
    # lock-guard's pass did not run: the waiver is not auditable here
    out, _ = run_passes([src("m.py", code)], only=["timeunit"])
    assert out == []
    # ... and IS dead once its pass runs
    out, _ = run_passes([src("m.py", code)], only=["locks"])
    assert rules_of(out) == {"waiver-dead"}


def test_bare_waiver_audited_only_on_full_runs():
    from tools.analyze import _dead_waivers

    files = [src("m.py", "x = 1  # analyze: ok\n")]
    assert _dead_waivers(files, {"timeunit-mix"}, {},
                         all_selected=False) == []
    out = _dead_waivers(files, {"timeunit-mix"}, {}, all_selected=True)
    assert [f.rule for f in out] == ["waiver-dead"]


def test_comment_line_waiver_credits_next_line_suppression():
    code = '''
    def f(now_ms, timeout_s):
        # analyze: ok timeunit-mix
        return now_ms + timeout_s
    '''
    out, _ = run_passes([src("m.py", code)], only=["timeunit"])
    assert out == []


def test_waiver_dead_live_tree_clean():
    """Every waiver in the production tree still suppresses a finding
    of every rule it names — the 27 reviewed exceptions are all
    load-bearing."""
    out, _ = run_passes(load_tree(REPO))
    assert [f for f in out if f.rule == "waiver-dead"] == []


def test_full_tree_runs_clean():
    """Acceptance bar: the repository carries ZERO non-baselined
    findings, and the baseline itself is EMPTY (every true positive
    was fixed; deliberate exceptions carry inline waivers)."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.analyze"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(open(os.path.join(
        REPO, "tools", "analyze", "baseline.json")).read()) == []
