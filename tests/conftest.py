"""Test configuration.

Tests run on the CPU backend with 8 virtual devices so multi-chip sharding
(mesh/shard_map paths) is exercised without TPU hardware. These env vars
must be set before jax is first imported anywhere in the test process.
"""

import os

# Force CPU even when the ambient environment selects a TPU platform
# (e.g. JAX_PLATFORMS=axon, whose plugin overrides the env var through
# jax.config): unit tests use tiny shapes where CPU is faster, and the
# virtual 8-device mesh needs the host platform. Set HSTREAM_TEST_PLATFORM
# to override (e.g. to run the suite on real TPU).
_platform = os.environ.get("HSTREAM_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (env vars above must precede first import)

jax.config.update("jax_platforms", _platform)
