"""Test configuration.

Tests run on the CPU backend with 8 virtual devices so multi-chip sharding
(mesh/shard_map paths) is exercised without TPU hardware. These env vars
must be set before jax is first imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
