"""Shared test helpers: readiness waits instead of sleeps.

SURVEY §4 flags the reference's sleep-based test sync ("FIXME: requires
a notification mechanism", RunSQLSpec.hs:54); QueryTask.attached is
that mechanism — set once the reader is attached to every source at its
start LSN (tasks.attached_lsns)."""

from __future__ import annotations

import time


def wait_attached(ctx, query_id: str, timeout: float = 10.0):
    """Block until the query's task is registered AND attached to its
    source streams; returns the task."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        task = ctx.running_queries.get(query_id)
        if task is not None and task.attached.wait(0.05):
            return task
        time.sleep(0.01)
    raise TimeoutError(f"query {query_id!r} never attached "
                       f"(running: {list(ctx.running_queries)})")


def wait_any_attached(ctx, timeout: float = 10.0, *, exclude=()):
    """Block until a running query task OUTSIDE `exclude` is attached
    (push queries have generated ids the test cannot predict; pass the
    pre-existing query ids so a stale attached task cannot satisfy the
    wait)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for qid, task in list(ctx.running_queries.items()):
            if qid in exclude:
                continue
            if getattr(task, "attached", None) is not None \
                    and task.attached.is_set():
                return task
        time.sleep(0.01)
    raise TimeoutError("no (new) query task attached")
