"""The device cost plane (ISSUE 18): HBM arena accounting, the
compiled-program inventory, and the flight recorder.

Accounting tests gate EXACTNESS: `device_plane_bytes()` must equal a
brute-force recompute (shape x itemsize per plane, computed here from
first principles, not via `nbytes`) for the fixed-window lattice, the
device join stores, and the session arena — before and after capacity
growth and code-space compaction. The inventory test pins one row per
distinct shape key under RetraceGuard; the flight-recorder tests pin
exactly one bundle per distress edge and survival across query
deletion (the black box outlives the aircraft).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import grpc
import numpy as np
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.engine import ColumnType, Schema
from hstream_tpu.engine.expr import Col
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec, SourceNode
from hstream_tpu.engine.executor import QueryExecutor
from hstream_tpu.engine.session import SessionExecutor
from hstream_tpu.engine.window import SessionWindow, TumblingWindow
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.http_gateway import serve_gateway
from hstream_tpu.server.main import serve
from hstream_tpu.sql.codegen import make_executor, stream_codegen

from helpers import wait_attached

BASE = 1_700_000_000_000

SCHEMA = Schema.of(k=ColumnType.STRING, v=ColumnType.FLOAT)


def _brute_bytes(planes) -> dict[str, int]:
    """Independent recompute of per-plane device bytes from shape and
    dtype — deliberately NOT via `nbytes`, so the accounting plane's
    own walk has something honest to be compared against."""
    out: dict[str, int] = {}
    for name, arr in dict(planes).items():
        n = 1
        for d in arr.shape:
            n *= int(d)
        nb = n * np.dtype(arr.dtype).itemsize
        if nb:
            out[str(name)] = nb
    return out


# ---- HBM arena accounting: exact against brute force -----------------------


def test_fixed_window_arena_bytes_exact_across_grow():
    node = AggregateNode(
        child=SourceNode("s", SCHEMA), group_keys=[Col("k")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
              AggSpec(AggKind.SUM, "s", input=Col("v"))],
        having=None, post_projections=[])
    ex = QueryExecutor(node, SCHEMA, emit_changes=False,
                       initial_keys=8, batch_capacity=256)
    rows = [{"k": f"k{i % 4}", "v": 1.0} for i in range(16)]
    ex.process(rows, [BASE + i for i in range(16)])
    got = ex.device_plane_bytes()
    assert got == _brute_bytes(ex.state)
    assert got and got == {k: v for k, v in got.items() if v > 0}
    before_total = sum(got.values())
    # key growth: > initial_keys distinct keys pads every keyed plane
    rows = [{"k": f"g{i}", "v": 1.0} for i in range(50)]
    ex.process(rows, [BASE + i for i in range(50)])
    got2 = ex.device_plane_bytes()
    assert got2 == _brute_bytes(ex.state)
    assert sum(got2.values()) > before_total


def test_join_store_bytes_exact_with_prefixed_planes():
    sql = ("SELECT l.k, COUNT(*) AS c FROM l INNER JOIN r "
           "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k "
           "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    ex = make_executor(stream_codegen(sql),
                       sample_rows=[{"k": "k0", "x": 1.0}])
    rng = np.random.default_rng(5)
    for b in range(8):
        rows = [{"k": f"k{int(i)}", "x": 1.0}
                for i in rng.integers(0, 30, 128)]
        ts = (BASE + b * 500
              + rng.integers(0, 400, 128).astype(np.int64)).tolist()
        ex.process(rows, ts, stream="l" if b % 2 else "r")
    assert ex._dev is not None, "device join path did not activate"
    want = {f"agg.{k}": v
            for k, v in _brute_bytes(ex._inner.state).items()}
    for side in ("l", "r"):
        for k, v in _brute_bytes(ex._dev["stores"][side]).items():
            want[f"{side}.{k}"] = v
    got = ex.device_plane_bytes()
    assert got == want
    # all three prefixes present: both stores and the inner lattice
    prefixes = {p.split(".", 1)[0] for p in got}
    assert {"l", "r", "agg"} <= prefixes


@pytest.mark.parametrize("mode", ["segment", "record"])
def test_session_arena_bytes_exact_across_compaction(mode):
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    node = AggregateNode(
        child=SourceNode("s", SCHEMA), group_keys=[Col("k")],
        window=SessionWindow(500, grace_ms=0), aggs=aggs,
        having=None, post_projections=[])
    ex = SessionExecutor(node, SCHEMA, emit_changes=False)
    ex.use_device_sessions = True
    ex.device_session_mode = mode
    assert ex.device_plane_bytes() == {}  # nothing resident yet
    ex._KEY_CACHE_MAX = 64  # force code-space compaction quickly
    rng = np.random.default_rng(3)
    before_compaction = None
    for b in range(8):
        ks = [f"k{b}_{int(i)}" for i in rng.integers(0, 40, 120)]
        ts = (BASE + b * 5000 + rng.integers(0, 400, 120)).tolist()
        ex.process([{"k": k, "v": 1.0} for k in ks], ts)
        if before_compaction is None and ex._dev is not None:
            before_compaction = ex.device_plane_bytes()
            assert before_compaction == _brute_bytes(ex._dev["arena"])
    assert ex._dev is not None
    assert ex.session_stats["remap_dispatches"] >= 1
    assert ex.device_plane_bytes() == _brute_bytes(ex._dev["arena"])
    assert before_compaction is not None and before_compaction


def test_plane_bytes_skips_non_arrays_and_empty():
    from hstream_tpu.stats.devicecost import plane_bytes

    got = plane_bytes({"a": np.zeros((4, 2), np.float32),
                       "empty": np.zeros((0,), np.int32),
                       "scalarish": 7})
    assert got == {"a": 32}


# ---- compiled-program inventory --------------------------------------------


def test_program_inventory_one_row_per_shape_key():
    import jax
    import jax.numpy as jnp

    from hstream_tpu.common.tracing import RetraceGuard, kernel_family
    from hstream_tpu.stats.devicecost import PROGRAMS

    assert PROGRAMS.install(), "compile funnel seam absent"
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    # build inputs OUTSIDE the guarded regions: the ones-fill is its
    # own (cached) compile and must not pollute the counts
    x8 = jnp.ones((8,), jnp.float32)
    x16 = jnp.ones((16,), jnp.float32)
    keys0 = {r["shape_key"] for r in PROGRAMS.rows()}

    with RetraceGuard() as g:
        with kernel_family("step", None):
            fn(x8).block_until_ready()
    assert g.count == 1
    new = [r for r in PROGRAMS.rows() if r["shape_key"] not in keys0]
    assert len(new) == 1, new
    row = new[0]
    assert row["compiles"] == 1 and row["compile_ms"] > 0
    assert row["family"] == "step"  # attributed to the active scope
    keys1 = keys0 | {row["shape_key"]}

    # same shape again: cache hit, no compile, NO new row
    with RetraceGuard() as g2:
        fn(x8).block_until_ready()
    assert g2.count == 0
    assert {r["shape_key"] for r in PROGRAMS.rows()} == keys1

    # a distinct shape is a distinct shape key: exactly one new row
    with RetraceGuard() as g3:
        fn(x16).block_until_ready()
    assert g3.count == 1
    new2 = [r for r in PROGRAMS.rows() if r["shape_key"] not in keys1]
    assert len(new2) == 1 and new2[0]["shape_key"] != row["shape_key"]

    s = PROGRAMS.summary()
    assert s["installed"] and s["programs"] >= 2
    assert s["total_compiles"] >= 2


def test_program_inventory_lru_bound_folds_into_evicted():
    from hstream_tpu.stats.devicecost import ProgramInventory

    inv = ProgramInventory()
    inv.MAX_ROWS = 4

    class _Exe:  # minimal stand-in for a LoadedExecutable
        def hlo_modules(self):
            return []

        def cost_analysis(self):
            return [{"flops": 10.0, "bytes accessed": 20.0}]

    for i in range(6):
        inv._record(_Exe(), 1.0, (None, f"module-{i}"))
    assert len(inv.rows()) == 4
    assert inv.evicted == 2
    assert inv.summary()["evicted"] == 2
    assert all(r["flops"] == 10.0 and r["bytes_accessed"] == 20.0
               for r in inv.rows())


# ---- flight recorder --------------------------------------------------------


@pytest.fixture(scope="module")
def stack():
    server, ctx = serve("127.0.0.1", 0, "mem://", metrics_port=0)
    addr = f"127.0.0.1:{ctx.port}"
    httpd, gw = serve_gateway(addr, port=0)
    base = f"http://127.0.0.1:{httpd.server_port}"
    channel = grpc.insecure_channel(addr)
    stub = HStreamApiStub(channel)
    yield base, stub, ctx
    channel.close()
    httpd.shutdown()
    gw.close()
    server.stop(grace=1)
    ctx.shutdown()


def _http(base, path):
    try:
        with urllib.request.urlopen(base + path) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _admin(stub, command, **kwargs):
    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command=command, args=rec.dict_to_struct(kwargs)))
    return json.loads(resp.result)


def test_flightrec_once_per_episode_and_survives_deletion(stack):
    """Breaker-open writes one bundle (crash_loop_open), the STALLED
    health transition writes one more (query_stalled) — and ONLY one
    each: re-evaluating health does not re-snapshot. The bundles stay
    readable over the wire after the query is deleted."""
    base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="frsrc"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM frsrc GROUP BY k, "
                   "TUMBLING (INTERVAL 1 SECOND) EMIT CHANGES;",
        id="qfr1"))
    task = wait_attached(ctx, q.id)
    # kill for real (crash: status stays RUNNING), then feed the
    # supervisor a crash loop until the breaker opens
    task.stop(crash=True)
    deadline = time.time() + 10
    while q.id in ctx.running_queries and time.time() < deadline:
        time.sleep(0.02)
    assert q.id not in ctx.running_queries
    info = ctx.persistence.get_query(q.id)
    sup = ctx.supervisor
    n_ev0 = len(ctx.events.query(kind="flightrec_written", limit=1000))
    for _ in range(sup.BREAKER_K):
        sup.note_death(info, RuntimeError("boom"))
    assert q.id in sup.status()["breaker_open"]

    bundles = ctx.flightrec.bundles(q.id)
    assert len(bundles) == 1
    assert bundles[0]["trigger"] == "crash_loop_open"
    ev = ctx.events.query(kind="flightrec_written", limit=1000)
    assert len(ev) == n_ev0 + 1 and ev[-1]["query"] == q.id

    # the STALLED transition: exactly one more bundle, with the
    # already-computed verdict inside
    code, body = _http(base, f"/queries/{q.id}/health")
    assert code == 200
    assert json.loads(body)["verdict"] == "STALLED"
    bundles = ctx.flightrec.bundles(q.id)
    assert len(bundles) == 2
    b = bundles[-1]
    assert b["trigger"] == "query_stalled"
    assert b["health"]["verdict"] == "STALLED"
    assert "crash_loop" in b["health"]["reasons"]
    # every postmortem section captured
    for section in ("events", "spans", "stat_ladder", "programs",
                    "hbm"):
        assert section in b, section
    assert any(e.get("kind") == "query_stalled" for e in b["events"])
    assert b["programs"]["summary"]["installed"] is True
    assert b["hbm"]["total"] == 0  # task already dead: nothing resident

    # re-evaluation is NOT a new episode: no third bundle
    _http(base, f"/queries/{q.id}/health")
    _http(base, f"/queries/{q.id}/health")
    assert len(ctx.flightrec.bundles(q.id)) == 2
    assert len(ctx.events.query(kind="flightrec_written",
                                limit=1000)) == n_ev0 + 2

    # served over the wire: admin verb and gateway route agree
    got = _admin(stub, "flightrec", query=q.id)
    assert got["query"] == q.id and len(got["bundles"]) == 2
    code, body = _http(base, f"/queries/{q.id}/flightrec")
    assert code == 200
    assert len(json.loads(body)["bundles"]) == 2

    # deleting the query must NOT shred the black box
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))
    code, _ = _http(base, f"/queries/{q.id}/health")
    assert code == 404  # the query is gone...
    code, body = _http(base, f"/queries/{q.id}/flightrec")
    assert code == 200
    assert len(json.loads(body)["bundles"]) == 2
    assert q.id in ctx.flightrec.summary()["queries"]


def test_flightrec_two_slot_rotation(stack):
    base, stub, ctx = stack
    fr = ctx.flightrec
    seqs = [fr.snapshot("rotq", trigger="query_stalled")["seq"]
            for _ in range(3)]
    kept = fr.bundles("rotq")
    assert [b["seq"] for b in kept] == seqs[-2:]  # newest two, in order
    assert fr.summary()["queries"]["rotq"] == 2
    # no-bundle query: admin verb raises the typed not-found error
    with pytest.raises(grpc.RpcError):
        _admin(stub, "flightrec", query="never-distressed")


def test_admin_programs_and_gateway_route(stack):
    base, stub, ctx = stack
    got = _admin(stub, "programs")
    assert got["summary"]["installed"] is True
    assert got["summary"]["programs"] == len(got["programs"])
    assert got["programs"], "server boot compiled nothing?"
    for row in got["programs"]:
        assert row["shape_key"] and row["compiles"] >= 1
    code, body = _http(base, "/programs")
    assert code == 200
    assert json.loads(body)["summary"]["installed"] is True


def test_device_gauges_on_live_metrics_match_brute_force(stack):
    """`device_hbm_bytes{query}` on a live server equals the
    brute-force plane recompute, per plane and in total — the
    acceptance-criteria exactness check, over /metrics."""
    base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="dgsrc"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM dgsrc GROUP BY k, "
                   "TUMBLING (INTERVAL 1 SECOND) EMIT CHANGES;",
        id="qdg1"))
    task = wait_attached(ctx, q.id)
    req = pb.AppendRequest(stream_name="dgsrc")
    now = int(time.time() * 1000)
    for i in range(8):
        req.records.append(rec.build_record({"k": f"k{i % 3}"},
                                            publish_time_ms=now + i))
    stub.Append(req)
    deadline = time.time() + 10
    while not task.device_plane_bytes() and time.time() < deadline:
        time.sleep(0.05)
    planes = task.device_plane_bytes()
    assert planes, "executor never became device-resident"
    ex = task.executor
    assert planes == _brute_bytes(ex.state)

    from hstream_tpu.stats.prometheus import render_metrics

    text = render_metrics(ctx)
    want_total = sum(planes.values())
    line = [ln for ln in text.splitlines()
            if ln.startswith(f'hstream_device_hbm_bytes{{query="{q.id}"')]
    assert line and line[0].split()[-1] == str(want_total)
    for plane, nb in planes.items():
        pl = [ln for ln in text.splitlines()
              if ln.startswith('hstream_device_arena_bytes{')
              and f'query="{q.id}"' in ln and f'plane="{plane}"' in ln]
        assert pl and pl[0].split()[-1] == str(nb), plane
    # process-total gauge folds every live query
    tot = [ln for ln in text.splitlines()
           if ln.startswith("hstream_device_hbm_total_bytes")]
    assert tot and int(float(tot[0].split()[-1])) >= want_total
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))
    # stale series sweep: the deleted query's series disappear
    deadline = time.time() + 10
    while q.id in ctx.running_queries and time.time() < deadline:
        time.sleep(0.02)
    text = render_metrics(ctx)
    assert f'hstream_device_hbm_bytes{{query="{q.id}"' not in text
