"""Negative-case validation matrix (reference ValidateSpec over
Validate.hs's ~750 LoC of semantic checks; VERDICT item 7)."""

import pytest

from hstream_tpu.common.errors import SQLError, SQLValidateError
from hstream_tpu.sql.refine import parse_and_refine

BAD = [
    # ---- aggregate placement ----
    ("SELECT k FROM s WHERE COUNT(*) > 1 EMIT CHANGES;",
     "aggregate.*WHERE"),
    ("SELECT COUNT(*) FROM s GROUP BY COUNT(*) EMIT CHANGES;",
     "aggregate|GROUP BY|trailing"),
    ("SELECT SUM(COUNT(*)) FROM s GROUP BY k EMIT CHANGES;",
     "nested aggregate"),
    # ---- aggregate arity ----
    ("SELECT SUM() FROM s GROUP BY k EMIT CHANGES;", "."),
    ("SELECT APPROX_QUANTILE(v, 1.5) FROM s GROUP BY k EMIT CHANGES;",
     "quantile.*\\[0, 1\\]"),
    ("SELECT APPROX_QUANTILE(v, -0.1) FROM s GROUP BY k EMIT CHANGES;",
     "quantile|APPROX_QUANTILE"),
    # ---- SELECT / GROUP BY consistency ----
    ("SELECT city, temp, COUNT(*) FROM s GROUP BY city EMIT CHANGES;",
     "neither aggregated nor in GROUP BY"),
    ("SELECT other + 1 AS x, COUNT(*) FROM s GROUP BY city "
     "EMIT CHANGES;", "neither aggregated nor in GROUP BY"),
    ("SELECT city FROM s GROUP BY city EMIT CHANGES;",
     "at least one aggregate"),
    ("SELECT city, COUNT(*) FROM s GROUP BY city, city EMIT CHANGES;",
     "duplicate GROUP BY"),
    # ---- HAVING ----
    ("SELECT k FROM s HAVING k > 1 EMIT CHANGES;",
     "HAVING requires GROUP BY"),
    ("SELECT k, COUNT(*) AS c FROM s GROUP BY k HAVING other > 1 "
     "EMIT CHANGES;", "neither aggregated nor in GROUP BY"),
    # ---- aliases ----
    ("SELECT COUNT(*) AS c, SUM(v) AS c FROM s GROUP BY k EMIT CHANGES;",
     "duplicate column alias"),
    # ---- windows ----
    ("SELECT COUNT(*) FROM s GROUP BY k, "
     "TUMBLING (INTERVAL 0 SECOND) EMIT CHANGES;", "positive interval"),
    ("SELECT COUNT(*) FROM s GROUP BY k, "
     "HOPPING (INTERVAL 10 SECOND, INTERVAL 3 SECOND) EMIT CHANGES;",
     "multiple of advance"),
    ("SELECT COUNT(*) FROM s GROUP BY k, "
     "HOPPING (INTERVAL 10 SECOND, INTERVAL 20 SECOND) EMIT CHANGES;",
     "advance cannot exceed|multiple of advance"),
    ("SELECT * FROM s GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
     "EMIT CHANGES;", "SELECT \\*|aggregate"),
    # ---- joins ----
    ("SELECT COUNT(*) FROM a INNER JOIN b WITHIN (INTERVAL 0 SECOND) "
     "ON a.k = b.k GROUP BY k EMIT CHANGES;", "positive interval"),
    ("SELECT COUNT(*) FROM a INNER JOIN b WITHIN (INTERVAL 5 SECOND) "
     "ON a.k > b.k GROUP BY k EMIT CHANGES;",
     "conjunction of equality"),
    ("SELECT COUNT(*) FROM a INNER JOIN b WITHIN (INTERVAL 5 SECOND) "
     "ON k = b.k GROUP BY k EMIT CHANGES;", "stream-qualified"),
    ("SELECT COUNT(*) FROM a INNER JOIN b WITHIN (INTERVAL 5 SECOND) "
     "ON a.k = a.j GROUP BY k EMIT CHANGES;", "relate both sides"),
    ("SELECT COUNT(*) FROM a INNER JOIN b WITHIN (INTERVAL 5 SECOND) "
     "ON a.k = c.k GROUP BY k EMIT CHANGES;",
     "unknown stream qualifier"),
    ("SELECT COUNT(*) FROM a INNER JOIN a WITHIN (INTERVAL 5 SECOND) "
     "ON a.k = a.k GROUP BY k EMIT CHANGES;", "self-join"),
    ("SELECT COUNT(*) FROM a AS l INNER JOIN a AS r "
     "WITHIN (INTERVAL 5 SECOND) ON l.k = r.k GROUP BY k EMIT CHANGES;",
     "self-join"),
    # ---- INSERT ----
    ("INSERT INTO s (a, b) VALUES (1);", "mismatch|value"),
    ("INSERT INTO s (a, a) VALUES (1, 2);", "duplicate INSERT column"),
    # ---- views ----
    ("CREATE VIEW v AS SELECT a FROM s;", "requires an aggregation"),
]


@pytest.mark.parametrize("sql,pat", BAD, ids=[b[0][:48] for b in BAD])
def test_rejected(sql, pat):
    import re

    with pytest.raises(SQLError) as ei:
        parse_and_refine(sql)
    assert re.search(pat, str(ei.value)), (pat, str(ei.value))


GOOD = [
    "SELECT city, COUNT(*) AS c FROM s GROUP BY city, "
    "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;",
    "SELECT city AS c, SUM(temp) FROM s WHERE temp > 0 GROUP BY city "
    "EMIT CHANGES;",
    "SELECT k, COUNT(*) AS c FROM s GROUP BY k HAVING c > 2 "
    "EMIT CHANGES;",
    "SELECT k, COUNT(*) AS n FROM s GROUP BY k "
    "HAVING COUNT(*) > 1 EMIT CHANGES;",
    "SELECT l.k, COUNT(*) FROM l INNER JOIN r "
    "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k AND l.j = r.j "
    "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;",
    "SELECT u, APPROX_QUANTILE(lat, 0.99) FROM s GROUP BY u, "
    "SESSION (INTERVAL 5 SECOND) EMIT CHANGES;",
    "INSERT INTO s (a, b) VALUES (1, 'x');",
    "SELECT a, b FROM s WHERE a > 1 EMIT CHANGES;",
]


@pytest.mark.parametrize("sql", GOOD, ids=[g[:48] for g in GOOD])
def test_accepted(sql):
    parse_and_refine(sql)


# ---- sampled-schema check (server-side half of validation) -----------------


def test_unknown_column_rejected_against_sampled_stream():
    import grpc

    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    server, ctx = serve("127.0.0.1", 0, "mem://")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="vs"))
        # empty stream: creation passes (nothing to check yet)
        q = stub.CreateQuery(pb.CreateQueryRequest(
            query_text="SELECT ghost, COUNT(*) AS c FROM vs "
                       "GROUP BY ghost EMIT CHANGES;"))
        stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))
        req = pb.AppendRequest(stream_name="vs")
        req.records.append(rec.build_record(
            {"city": "sf", "temp": 20.0},
            publish_time_ms=1_700_000_000_000))
        stub.Append(req)
        # now the sample knows the fields: unknown columns are errors
        with pytest.raises(grpc.RpcError) as ei:
            stub.CreateQuery(pb.CreateQueryRequest(
                query_text="SELECT ghost, COUNT(*) AS c FROM vs "
                           "GROUP BY ghost EMIT CHANGES;"))
        assert "ghost" in ei.value.details()
        with pytest.raises(grpc.RpcError):
            stub.ExecuteQuery(pb.CommandQuery(
                stmt_text="CREATE VIEW badv AS SELECT city, "
                          "COUNT(nope) AS c FROM vs GROUP BY city;"))
        # known columns still fine
        q2 = stub.CreateQuery(pb.CreateQueryRequest(
            query_text="SELECT city, COUNT(*) AS c FROM vs "
                       "GROUP BY city EMIT CHANGES;"))
        assert q2.id
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()
