"""Tests for the bit-packed v2 wire codec (engine/transport.py)."""
from __future__ import annotations

import numpy as np
import pytest

from hstream_tpu.engine import transport as tp


def roundtrip(combo, dt_base, words, cap, n):
    import jax

    key_ids, ts, valid, cols = jax.jit(
        lambda w: tp.decode_batch(w, combo, cap, np.int32(n),
                                  np.int32(dt_base)),
        static_argnums=())(words)
    return (np.asarray(key_ids), np.asarray(ts), np.asarray(valid),
            {k: np.asarray(v) for k, v in cols.items()})


def test_u8_u16_roundtrip():
    t = tp.BitpackTransport()
    n, cap = 300, 512
    kids = np.arange(n, dtype=np.int32) % 200          # fits u8
    ts = np.arange(n, dtype=np.int64) * 3 + 1000       # span ~900 -> u16
    cols = {"x": (np.arange(n, dtype=np.int32) * 7) % 50000}  # u16
    combo, base, words = t.encode(cap, n, kids, ts, cols,
                                  (("x", "i32"),))
    k, ts2, valid, dcols = roundtrip(combo, base, words, cap, n)
    assert valid[:n].all() and not valid[n:].any()
    np.testing.assert_array_equal(k[:n], kids)
    np.testing.assert_array_equal(ts2[:n], ts)
    np.testing.assert_array_equal(dcols["x"][:n], cols["x"])


def test_dec16_bitexact_roundtrip():
    t = tp.BitpackTransport()
    n = cap = 256
    kids = np.zeros(n, np.int32)
    ts = np.zeros(n, np.int64)
    # decimal-quantized floats (1 decimal place, codec-canonical f32
    # representation q * f32(0.1)), incl. negatives
    raw = np.random.default_rng(0).normal(20, 5, n)
    vals = (np.rint(raw * 10).astype(np.float32) * np.float32(0.1))
    combo, base, words = t.encode(cap, n, kids, ts, {"temp": vals},
                                  (("temp", "f32"),))
    plan = [p for p in combo if p.name == "temp"][0]
    assert plan.enc == tp.ENC_DEC and plan.scale == 10
    _, _, _, dcols = roundtrip(combo, base, words, cap, n)
    # bit-exact: the encoder verified decode(encode(v)) == v
    np.testing.assert_array_equal(dcols["temp"][:n].view(np.int32),
                                  vals.view(np.int32))


def test_float_fallback_raw32():
    t = tp.BitpackTransport()
    n = cap = 256
    vals = np.random.default_rng(1).normal(0, 1, n).astype(np.float32)
    combo, base, words = t.encode(cap, n, np.zeros(n, np.int32),
                                  np.zeros(n, np.int64), {"v": vals},
                                  (("v", "f32"),))
    plan = [p for p in combo if p.name == "v"][0]
    assert plan.enc == tp.ENC_RAW_F32
    _, _, _, dcols = roundtrip(combo, base, words, cap, n)
    np.testing.assert_array_equal(dcols["v"][:n], vals)
    # sticky: stays demoted even for a later decimal-friendly batch
    ints = np.arange(n, dtype=np.float32)
    combo2, _, _ = t.encode(cap, n, np.zeros(n, np.int32),
                            np.zeros(n, np.int64), {"v": ints},
                            (("v", "f32"),))
    assert [p for p in combo2 if p.name == "v"][0].enc == tp.ENC_RAW_F32


def test_monotone_widening():
    t = tp.BitpackTransport()
    n = cap = 256
    small = np.arange(n, dtype=np.int32) % 100
    big = np.arange(n, dtype=np.int32) * 300
    args = (np.zeros(n, np.int64), {"x": small}, (("x", "i32"),))
    c1, _, _ = t.encode(cap, n, small, *args)
    assert [p for p in c1 if p.name == "x"][0].enc == tp.ENC_U8
    c2, _, _ = t.encode(cap, n, small, np.zeros(n, np.int64), {"x": big},
                        (("x", "i32"),))
    assert [p for p in c2 if p.name == "x"][0].enc == tp.ENC_RAW_I32
    # never narrows back
    c3, _, _ = t.encode(cap, n, small, *args)
    assert [p for p in c3 if p.name == "x"][0].enc == tp.ENC_RAW_I32


def test_valid_and_null_streams():
    t = tp.BitpackTransport()
    n, cap = 100, 256
    valid = np.ones(n, np.bool_)
    valid[::3] = False
    nullm = np.zeros(n, np.bool_)
    nullm[5:10] = True
    combo, base, words = t.encode(
        cap, n, np.zeros(n, np.int32), np.zeros(n, np.int64),
        {"x": np.ones(n, np.int32)}, (("x", "i32"),),
        valid=valid, null_streams={"__null_a0": nullm})
    _, _, v, cols = roundtrip(combo, base, words, cap, n)
    np.testing.assert_array_equal(v[:n], valid)
    assert not v[n:].any()
    np.testing.assert_array_equal(cols["__null_a0"][:n], nullm)


def test_bool_and_negative_ts_delta():
    t = tp.BitpackTransport()
    n = cap = 256
    ts = 5000 - np.arange(n, dtype=np.int64)  # decreasing; base = min
    flags = (np.arange(n) % 2 == 0)
    combo, base, words = t.encode(cap, n, np.zeros(n, np.int32), ts,
                                  {"b": flags}, (("b", "bool"),))
    _, ts2, _, cols = roundtrip(combo, base, words, cap, n)
    np.testing.assert_array_equal(ts2[:n], ts)
    np.testing.assert_array_equal(cols["b"][:n], flags)


def test_wire_bytes_headline_shape():
    """The headline query's wire footprint: u16 kid + u8 dt + dec16 value
    = 5 bytes/event (vs 16 for the naive int32 transport)."""
    t = tp.BitpackTransport()
    n = cap = 1024
    kids = np.arange(n, dtype=np.int32) % 1000
    ts = np.arange(n, dtype=np.int64) % 200
    temps = (np.rint(np.random.default_rng(2).normal(20, 5, n) * 10)
             .astype(np.float32) * np.float32(0.1))
    combo, base, words = t.encode(cap, n, kids, ts, {"temp": temps},
                                  (("temp", "f32"),))
    assert tp.wire_bytes(combo, cap) == cap * 5
