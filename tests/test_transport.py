"""Tests for the bit-packed v3 wire codec (engine/transport.py)."""
from __future__ import annotations

import numpy as np
import pytest

from hstream_tpu.engine import transport as tp


def roundtrip(combo, bases, words, cap, n):
    import jax

    key_ids, ts, valid, cols = jax.jit(
        lambda w, b: tp.decode_batch(w, combo, cap, np.int32(n), b),
        static_argnums=())(words, bases)
    return (np.asarray(key_ids), np.asarray(ts), np.asarray(valid),
            {k: np.asarray(v) for k, v in cols.items()})


def plan_of(combo, name):
    return [p for p in combo if p.name == name][0]


def test_uint_roundtrip():
    t = tp.BitpackTransport()
    n, cap = 300, 512
    kids = np.arange(n, dtype=np.int32) % 200
    ts = np.arange(n, dtype=np.int64) * 3 + 1000     # sorted -> delta pack
    cols = {"x": (np.arange(n, dtype=np.int32) * 7) % 50000}
    combo, bases, words = t.encode(cap, n, kids, ts, cols,
                                   (("x", "i32"),))
    k, ts2, valid, dcols = roundtrip(combo, bases, words, cap, n)
    assert valid[:n].all() and not valid[n:].any()
    np.testing.assert_array_equal(k[:n], kids)
    np.testing.assert_array_equal(ts2[:n], ts)
    np.testing.assert_array_equal(dcols["x"][:n], cols["x"])


def test_sorted_ts_delta_packs_tiny():
    """A sorted ms-resolution time column costs ~1 bit/event (bpd)."""
    t = tp.BitpackTransport()
    n = cap = 1 << 12
    ts = np.sort(np.random.default_rng(0).integers(0, n // 4, n)).astype(
        np.int64)
    combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32), ts,
                                   {}, ())
    plan = plan_of(combo, "__dt")
    assert plan.enc == tp.ENC_BPD and plan.bits <= 2
    _, ts2, _, _ = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(ts2[:n], ts)


def test_unsorted_ts_demotes_delta_permanently():
    t = tp.BitpackTransport()
    n = cap = 256
    down = 5000 - np.arange(n, dtype=np.int64)   # decreasing
    combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32), down,
                                   {}, ())
    assert plan_of(combo, "__dt").enc == tp.ENC_BP
    _, ts2, _, _ = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(ts2[:n], down)
    up = np.arange(n, dtype=np.int64)            # sorted again
    combo2, _, _ = t.encode(cap, n, np.zeros(n, np.int32), up, {}, ())
    assert plan_of(combo2, "__dt").enc == tp.ENC_BP  # sticky demotion


def test_dec_bitexact_roundtrip():
    t = tp.BitpackTransport()
    n = cap = 256
    kids = np.zeros(n, np.int32)
    ts = np.zeros(n, np.int64)
    # decimal-quantized floats (1 decimal place, codec-canonical f32
    # representation q * f32(0.1)), incl. negatives
    raw = np.random.default_rng(0).normal(20, 5, n)
    vals = (np.rint(raw * 10).astype(np.float32) * np.float32(0.1))
    combo, bases, words = t.encode(cap, n, kids, ts, {"temp": vals},
                                   (("temp", "f32"),))
    plan = plan_of(combo, "temp")
    assert plan.enc == tp.ENC_DEC and plan.scale == 10
    assert plan.bits <= 10  # range-packed, not 16 fixed
    _, _, _, dcols = roundtrip(combo, bases, words, cap, n)
    # bit-exact: the encoder verified decode(encode(v)) == v
    np.testing.assert_array_equal(dcols["temp"][:n].view(np.int32),
                                  vals.view(np.int32))


def test_constant_column_zero_bits():
    t = tp.BitpackTransport()
    n = cap = 256
    const = np.full(n, 7, np.int32)
    combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32),
                                   np.zeros(n, np.int64), {"x": const},
                                   (("x", "i32"),))
    assert plan_of(combo, "x").bits == 0
    _, _, _, dcols = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(dcols["x"][:n], const)


def test_float_fallback_raw32():
    t = tp.BitpackTransport()
    n = cap = 256
    vals = np.random.default_rng(1).normal(0, 1, n).astype(np.float32)
    combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32),
                                   np.zeros(n, np.int64), {"v": vals},
                                   (("v", "f32"),))
    assert plan_of(combo, "v").enc == tp.ENC_RAW_F32
    _, _, _, dcols = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(dcols["v"][:n], vals)
    # sticky: stays demoted even for a later decimal-friendly batch
    ints = np.arange(n, dtype=np.float32)
    combo2, _, _ = t.encode(cap, n, np.zeros(n, np.int32),
                            np.zeros(n, np.int64), {"v": ints},
                            (("v", "f32"),))
    assert plan_of(combo2, "v").enc == tp.ENC_RAW_F32


def test_monotone_widening():
    t = tp.BitpackTransport()
    n = cap = 256
    small = np.arange(n, dtype=np.int32) % 100       # 7 bits
    big = np.arange(n, dtype=np.int32) * 300         # ~17 bits
    args = (np.zeros(n, np.int64), {"x": small}, (("x", "i32"),))
    c1, _, _ = t.encode(cap, n, small, *args)
    assert plan_of(c1, "x").bits == 8    # 7 bits needed, ladder -> 8
    c2, _, _ = t.encode(cap, n, small, np.zeros(n, np.int64), {"x": big},
                        (("x", "i32"),))
    assert plan_of(c2, "x").bits == 20   # 17 needed, ladder -> 20
    # never narrows back
    c3, _, _ = t.encode(cap, n, small, *args)
    assert plan_of(c3, "x").bits == 20


def test_negative_ints_and_wide_fallback():
    t = tp.BitpackTransport()
    n = cap = 256
    negs = np.arange(n, dtype=np.int32) - 128        # base handles < 0
    combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32),
                                   np.zeros(n, np.int64), {"x": negs},
                                   (("x", "i32"),))
    assert plan_of(combo, "x").enc == tp.ENC_BP
    _, _, _, dcols = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(dcols["x"][:n], negs)
    wide = np.array([-(1 << 31) + 1] + [0] * (n - 1), np.int32)
    c2, b2, w2 = t.encode(cap, n, np.zeros(n, np.int32),
                          np.zeros(n, np.int64), {"x": wide},
                          (("x", "i32"),))
    assert plan_of(c2, "x").enc == tp.ENC_RAW_I32
    _, _, _, d2 = roundtrip(c2, b2, w2, cap, n)
    np.testing.assert_array_equal(d2["x"][:n], wide)


def test_valid_and_null_streams():
    t = tp.BitpackTransport()
    n, cap = 100, 256
    valid = np.ones(n, np.bool_)
    valid[::3] = False
    nullm = np.zeros(n, np.bool_)
    nullm[5:10] = True
    combo, bases, words = t.encode(
        cap, n, np.zeros(n, np.int32), np.zeros(n, np.int64),
        {"x": np.ones(n, np.int32)}, (("x", "i32"),),
        valid=valid, null_streams={"__null_a0": nullm})
    _, _, v, cols = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(v[:n], valid)
    assert not v[n:].any()
    np.testing.assert_array_equal(cols["__null_a0"][:n], nullm)


def test_bool_roundtrip():
    t = tp.BitpackTransport()
    n = cap = 256
    flags = (np.arange(n) % 2 == 0)
    combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32),
                                   np.zeros(n, np.int64),
                                   {"b": flags}, (("b", "bool"),))
    _, _, _, cols = roundtrip(combo, bases, words, cap, n)
    np.testing.assert_array_equal(cols["b"][:n], flags)


@pytest.mark.parametrize("bits", [1, 3, 7, 10, 13, 16, 21, 29, 32])
def test_bitpack_widths_roundtrip(bits):
    """Property: pack/unpack is exact at every width, odd sizes incl."""
    rng = np.random.default_rng(bits)
    for n in (1, 31, 32, 33, 257):
        cap = max(256, 1 << int(np.ceil(np.log2(n))))
        hi = (1 << bits) - 1
        vals = rng.integers(0, hi + 1 if hi < (1 << 31) else (1 << 31),
                            size=n).astype(np.int64)
        t = tp.BitpackTransport()
        combo, bases, words = t.encode(cap, n, np.zeros(n, np.int32),
                                       np.zeros(n, np.int64),
                                       {"x": vals}, (("x", "i32"),))
        _, _, _, cols = roundtrip(combo, bases, words, cap, n)
        np.testing.assert_array_equal(cols["x"][:n], vals)


def test_numpy_fallback_matches_native(monkeypatch):
    """The pure-numpy packer (no g++ environments) must produce the
    same words as the native kernels."""
    n = cap = 1 << 10
    rng = np.random.default_rng(7)
    kids = rng.integers(0, 1000, n).astype(np.int32)
    ts = np.sort(rng.integers(0, 500, n)).astype(np.int64)
    temps = (np.rint(rng.normal(20, 5, n) * 10)
             .astype(np.float32) * np.float32(0.1))
    flags = rng.integers(0, 2, n).astype(np.bool_)
    args = (cap, n, kids, ts, {"temp": temps, "b": flags},
            (("temp", "f32"), ("b", "bool")))
    c_native, b_native, w_native = tp.BitpackTransport().encode(*args)
    monkeypatch.setattr(tp, "_lib", lambda: None)
    c_np, b_np, w_np = tp.BitpackTransport().encode(*args)
    assert c_native == c_np
    np.testing.assert_array_equal(b_native, b_np)
    np.testing.assert_array_equal(w_native, w_np)


def test_wire_bytes_headline_shape():
    """The headline query's wire footprint: 10-bit kid + 1-bit sorted dt
    + ~10-bit dec value ~ 2.7 bytes/event (vs 5 byte-aligned, 16 naive).
    """
    t = tp.BitpackTransport()
    n = cap = 1 << 13
    rng = np.random.default_rng(2)
    kids = rng.integers(0, 1000, n).astype(np.int32)
    ts = np.sort(rng.integers(0, 200, n)).astype(np.int64)
    temps = (np.rint(rng.normal(20, 5, n) * 10)
             .astype(np.float32) * np.float32(0.1))
    combo, bases, words = t.encode(cap, n, kids, ts, {"temp": temps},
                                   (("temp", "f32"),))
    bpe = tp.wire_bytes(combo, cap) / cap
    assert bpe < 3.0, (bpe, combo)
