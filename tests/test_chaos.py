"""Seeded chaos scenarios (ISSUE 8): every failure mode the recovery
code claims to handle is provoked ON DEMAND through the deterministic
fault-injection registry, and the run must heal itself — final windowed
results identical to a no-fault run (at-least-once replay from the
snapshot's read positions, dedup by LSN), recovery within bounded
restarts, and the query ends RUNNING (FAILED only via the crash-loop
breaker, which is the verdict under test there).

Scenarios: crash mid-batch (supervised restart), crash loop (breaker
opens, operator reset recovers), torn snapshot write (two-slot
fallback + gap replay), checkpoint corruption (boot survives, replay
skips the torn delta), follower flap (jittered reconnect backoff, no
hot spin), device activation failure (host reference-path fallback).
All schedules are seeded — a failing run replays identically.

Runtime-budgeted: the whole file is the CI chaos smoke step and must
stay well under 60s on the CPU backend.
"""

from __future__ import annotations

import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.common.faultinject import FAULTS, FaultRegistry, InjectedFault
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve
from hstream_tpu.server.persistence import TaskStatus
from hstream_tpu.server.tasks import QueryTask, snapshot_key

from helpers import wait_attached
from hstream_tpu.sql.codegen import make_executor, stream_codegen

BASE = 1_700_000_000_000


@pytest.fixture(autouse=True)
def _disarm_all_faults():
    """FAULTS is process-global: every test starts and ends disarmed so
    an armed site can never leak into a neighbour."""
    FAULTS.disarm()
    yield
    FAULTS.disarm()
    QueryTask.snapshot_interval_ms = 1000


# ---- harness helpers --------------------------------------------------------


def _serve():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    return server, ctx, HStreamApiStub(channel), channel


def append_rows(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    return stub.Append(req)


def _poll_view(stub, view, pred, timeout=30):
    rows = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=f"SELECT * FROM {view};"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        if pred(rows):
            return rows
        time.sleep(0.1)
    return rows


def _norm(rows):
    return sorted(
        tuple(sorted((k, round(v, 6) if isinstance(v, float) else v)
                     for k, v in r.items()))
        for r in rows)


def _closed_counts(rows):
    """city -> c for the closed [BASE, BASE+10s) window."""
    return {r["city"]: r["c"] for r in rows if r.get("winStart") == BASE}


def _event_kinds(ctx):
    return {e["kind"] for e in ctx.events.query(limit=1000)}


def _wait(cond, timeout=20.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


# ---- crash mid-batch: supervised restart ------------------------------------


def _city_view_flow(stub, ctx, *, stream, view, arm=None, recover=None):
    """Shared scenario: (arm faults) -> ingest A -> (wait for recovery)
    -> ingest the closer -> return the closed-window counts. The
    no-fault run of this exact flow is the equivalence reference."""
    stub.CreateStream(pb.Stream(stream_name=stream))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text=f"CREATE VIEW {view} AS SELECT city, COUNT(*) AS c "
                  f"FROM {stream} GROUP BY city, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    qid = f"view-{view}"
    wait_attached(ctx, qid)
    if arm is not None:
        arm()  # BEFORE the first chunk is read: deterministic hits
    append_rows(stub, stream,
                [{"city": "sf"}, {"city": "sf"}, {"city": "la"}],
                [BASE, BASE + 10, BASE + 20])
    if recover is not None:
        recover(qid)
    append_rows(stub, stream, [{"city": "zz"}], [BASE + 30_000])
    rows = _poll_view(
        stub, view,
        lambda rs: any(r.get("city") == "sf"
                       and r.get("winStart") == BASE for r in rs))
    return qid, _closed_counts(rows)


def test_crash_mid_batch_supervised_restart_exact_results():
    """task.step=fail:1 kills the task on its FIRST read chunk — before
    processing or checkpointing it. The supervisor must restart the
    query from the last snapshot (none yet: the trim point), the chunk
    replays, and the closed window matches the no-fault run exactly."""
    # no-fault reference
    server, ctx, stub, channel = _serve()
    try:
        _, want = _city_view_flow(stub, ctx, stream="cs0", view="cv0")
    finally:
        channel.close(); server.stop(grace=1); ctx.shutdown()
    assert want == {"sf": 2, "la": 1}

    server, ctx, stub, channel = _serve()
    try:
        sup = ctx.supervisor
        sup.BACKOFF_BASE_S = 0.05  # keep the smoke fast

        def recover(qid):
            assert _wait(lambda: sup.restarts >= 1), sup.status()
            wait_attached(ctx, qid)

        qid, got = _city_view_flow(
            stub, ctx, stream="cs1", view="cv1",
            arm=lambda: ctx.faults.arm("task.step", "fail:1"),
            recover=recover)
        assert got == want
        # recovery was bounded and the query ended RUNNING
        assert ctx.supervisor.restarts == 1
        assert qid in ctx.running_queries
        assert ctx.persistence.get_query(qid).status == TaskStatus.RUNNING
        kinds = _event_kinds(ctx)
        assert "fault_injected" in kinds
        assert "query_restart_scheduled" in kinds
        assert ctx.stats.stream_stat_get("query_restarts", qid) == 1
    finally:
        channel.close(); server.stop(grace=1); ctx.shutdown()


def test_crash_loop_opens_breaker_then_operator_reset_recovers():
    """task.step=fail:1:100 makes EVERY chunk fatal: K deaths inside W
    seconds must open the breaker (status FAILED, crash_loop_open
    journal + gauge) instead of a restart storm. An operator
    RestartQuery closes the breaker; with the fault cleared the query
    recovers to the exact no-fault result."""
    server, ctx, stub, channel = _serve()
    try:
        sup = ctx.supervisor
        sup.BACKOFF_BASE_S = 0.05
        sup.BACKOFF_CAP_S = 0.2

        def recover(qid):
            # the armed chunk is fatal; each supervised restart
            # re-reads it and dies again until the breaker opens
            assert _wait(
                lambda: qid in sup.status()["breaker_open"]), sup.status()
            assert ctx.persistence.get_query(qid).status == \
                TaskStatus.FAILED
            assert "crash_loop_open" in _event_kinds(ctx)
            assert ctx.stats.gauges_snapshot().get(
                ("crash_loop_open", qid)) == 1.0
            # breaker open: no further restarts are scheduled
            assert sup.status()["pending"] == {}
            # operator intervention: clear the fault, reset via
            # RestartQuery (the same verb a human would use) once the
            # dying task has finished unregistering itself
            ctx.faults.disarm("task.step")
            assert _wait(lambda: qid not in ctx.running_queries)
            stub.RestartQuery(pb.RestartQueryRequest(id=qid))
            wait_attached(ctx, qid)

        qid, got = _city_view_flow(
            stub, ctx, stream="cs2", view="cv2",
            arm=lambda: ctx.faults.arm("task.step", "fail:1:100"),
            recover=recover)
        assert got == {"sf": 2, "la": 1}
        assert ctx.persistence.get_query(qid).status == TaskStatus.RUNNING
        assert qid not in ctx.supervisor.status()["breaker_open"]
        assert ctx.stats.gauges_snapshot().get(
            ("crash_loop_open", qid)) is None
    finally:
        channel.close(); server.stop(grace=1); ctx.shutdown()


# ---- torn snapshot: two-slot fallback + gap replay --------------------------


def test_torn_snapshot_falls_back_to_previous_slot_and_replays():
    """snapshot.persist=torn:1:7 truncates the NEXT snapshot blob at a
    seeded cut. The pointer then names a corrupt slot; restore must
    fall back to the previous good slot, journal snapshot_corrupt,
    bump snapshot_fallbacks, and REPLAY the gap — the closed window is
    exact, not undercounted."""
    server, ctx, stub, channel = _serve()
    QueryTask.snapshot_interval_ms = 50
    try:
        stub.CreateStream(pb.Stream(stream_name="ts1"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW tv1 AS SELECT city, COUNT(*) AS c "
                      "FROM ts1 GROUP BY city, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        qid = "view-tv1"
        wait_attached(ctx, qid)
        # A: establish a GOOD snapshot covering (some prefix of) A
        append_rows(stub, "ts1",
                    [{"city": "sf"}, {"city": "sf"}, {"city": "la"}],
                    [BASE, BASE + 10, BASE + 20])
        assert _wait(lambda: ctx.store.meta_get(snapshot_key(qid))
                     is not None)
        _poll_view(stub, "tv1", lambda rs: any(r.get("c") == 2
                                               for r in rs))
        # the NEXT persist (covering A2) is torn mid-blob
        ctx.faults.arm("snapshot.persist", "torn:1:7")
        append_rows(stub, "ts1", [{"city": "sf"}], [BASE + 30])
        assert _wait(lambda: ctx.faults.status().get(
            "snapshot.persist", {}).get("injected", 0) >= 1)
        # crash while the pointer names the torn slot
        task = ctx.running_queries[qid]
        task.snapshot_interval_ms = 10**9  # no rescue snapshot
        task.stop(crash=True)
        ctx.faults.disarm("snapshot.persist")
        stub.RestartQuery(pb.RestartQueryRequest(id=qid))
        wait_attached(ctx, qid)
        # restore fell back past the torn slot and replayed the gap
        kinds = _event_kinds(ctx)
        assert "snapshot_corrupt" in kinds
        assert ctx.stats.stream_stat_get("snapshot_fallbacks", qid) >= 1
        # B + the closer: the window must hold A + A2 + B exactly once
        append_rows(stub, "ts1", [{"city": "sf"}], [BASE + 40])
        append_rows(stub, "ts1", [{"city": "zz"}], [BASE + 30_000])
        rows = _poll_view(
            stub, "tv1",
            lambda rs: any(r.get("city") == "sf" and r.get("c") == 4
                           and r.get("winStart") == BASE for r in rs))
        closed = _closed_counts(rows)
        assert closed.get("sf") == 4, rows
        assert closed.get("la") == 1, rows
        assert ctx.persistence.get_query(qid).status == TaskStatus.RUNNING
    finally:
        QueryTask.snapshot_interval_ms = 1000
        channel.close(); server.stop(grace=1); ctx.shutdown()


# ---- checkpoint corruption: boot survives, replay skips the torn delta ------


def test_checkpoint_torn_delta_survives_server_restart(tmp_path):
    """checkpoint.flush=torn:1:5 truncates one checkpoint-log delta
    mid-JSON. A full server restart on the same store must BOOT (not
    crash in LogCheckpointStore replay), journal checkpoint_corrupt,
    and produce the exact no-fault window — a skipped delta only makes
    the reader replay more."""
    store_dir = str(tmp_path / "store")
    server, ctx, = serve("127.0.0.1", 0, store_dir)
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    QueryTask.snapshot_interval_ms = 50
    try:
        stub.CreateStream(pb.Stream(stream_name="ck1"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW ckv AS SELECT city, COUNT(*) AS c "
                      "FROM ck1 GROUP BY city, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        qid = "view-ckv"
        wait_attached(ctx, qid)
        # the FIRST checkpoint write for A is torn mid-document
        ctx.faults.arm("checkpoint.flush", "torn:1:5")
        append_rows(stub, "ck1",
                    [{"city": "sf"}, {"city": "sf"}, {"city": "la"}],
                    [BASE, BASE + 10, BASE + 20])
        assert _wait(lambda: ctx.faults.status().get(
            "checkpoint.flush", {}).get("injected", 0) >= 1)
        _poll_view(stub, "ckv", lambda rs: any(r.get("c") == 2
                                               for r in rs))
        ctx.faults.disarm()
        channel.close(); server.stop(grace=1); ctx.shutdown()

        # reboot on the same directory: replay must skip the torn
        # delta loudly instead of failing construction
        server, ctx = serve("127.0.0.1", 0, store_dir)
        channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
        stub = HStreamApiStub(channel)
        assert ctx.ckp_store.replay_skipped >= 1
        assert "checkpoint_corrupt" in _event_kinds(ctx)
        wait_attached(ctx, qid)
        append_rows(stub, "ck1", [{"city": "zz"}], [BASE + 30_000])
        rows = _poll_view(
            stub, "ckv",
            lambda rs: any(r.get("city") == "sf" and r.get("c") == 2
                           and r.get("winStart") == BASE for r in rs))
        closed = _closed_counts(rows)
        assert closed.get("sf") == 2, rows
        assert closed.get("la") == 1, rows
    finally:
        QueryTask.snapshot_interval_ms = 1000
        channel.close(); server.stop(grace=1); ctx.shutdown()


# ---- follower flap: jittered reconnect backoff ------------------------------


def test_follower_flap_backs_off_then_converges():
    """store.follower.connect=fail:1:3 fails the sender's first three
    connect attempts. The reconnect loop must back off (growing waits,
    not a hot spin) and the follower must converge once the site goes
    quiet — with every injected hit accounted for."""
    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import ReplicatedStore, serve_follower

    import socket

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    follower_store = open_store("mem://")
    fsrv, svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    FAULTS.arm("store.follower.connect", "fail:1:3")
    leader = ReplicatedStore(open_store("mem://"),
                             [f"127.0.0.1:{port}"],
                             replication_factor=2)
    try:
        leader.create_log(5)
        # appends DURING the flap: stored locally, degraded acks
        leader.append(5, b"one")
        leader.append(5, b"two")
        f = leader._followers[0]
        # the flap drove the backoff up (three failures -> three
        # growing scheduled waits; seeded jitter stays within 25%)
        assert _wait(lambda: FAULTS.status()
                     ["store.follower.connect"]["injected"] >= 3,
                     timeout=15)
        # once the site stops firing, the follower converges and the
        # backoff state resets
        assert _wait(lambda: svc.applied_seq >= leader.oplog_seq,
                     timeout=20), (svc.applied_seq, leader.oplog_seq)
        assert _wait(lambda: f.connect_attempts == 0, timeout=10)
        assert f.last_backoff_s == 0.0
        st = leader.follower_status()[0]
        assert st["alive"] is True
        assert FAULTS.status()["store.follower.connect"]["injected"] == 3
    finally:
        FAULTS.disarm()
        leader.close()
        fsrv.stop(grace=1)


# ---- device activation failure: host reference-path fallback ----------------


def _feed(sql, batches, sample):
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=sample)
    out = []
    for rows, ts, *origin in batches:
        if origin:
            out.extend(ex.process(rows, ts, stream=origin[0]))
        else:
            out.extend(ex.process(rows, ts))
    out.extend(ex.flush_changes())
    return ex, out


def test_fused_close_activation_failure_degrades_exactly():
    """device.activate=fail:1 fires inside the first fused window
    close. The executor must fall back to the retained per-slot
    reference close — identical rows, query alive — and stay degraded
    (counted in device_fallbacks) for later closes too."""
    from hstream_tpu.engine import (
        AggKind,
        AggSpec,
        AggregateNode,
        ColumnType,
        QueryExecutor,
        Schema,
        SourceNode,
        TumblingWindow,
    )
    from hstream_tpu.engine.expr import Col

    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)

    def run(fault):
        node = AggregateNode(
            child=SourceNode("s", schema), group_keys=[Col("device")],
            window=TumblingWindow(10_000, grace_ms=0),
            aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
                  AggSpec(AggKind.SUM, "s", input=Col("temp"))],
            having=None, post_projections=[])
        ex = QueryExecutor(node, schema, emit_changes=False,
                           initial_keys=8, batch_capacity=256)
        if fault:
            FAULTS.arm("device.activate", "fail:1")
        out = []
        batches = [
            ([{"device": "a", "temp": 1.0},
              {"device": "b", "temp": 5.0}], [BASE, BASE + 100]),
            ([{"device": "a", "temp": 2.0}], [BASE + 5000]),
            ([{"device": "c", "temp": 9.0}], [BASE + 15_000]),  # w1
            ([{"device": "c", "temp": 1.0}], [BASE + 30_000]),  # w2
        ]
        for rows, ts in batches:
            out.extend(ex.process(rows, ts))
        FAULTS.disarm()
        return ex, list(out)

    _, want = run(fault=False)
    ex, got = run(fault=True)
    assert _norm(got) == _norm(want)
    assert ex.device_fallbacks == 1
    assert ex._fused_close_ok is False
    assert len(want) > 0  # both closes actually emitted rows


def test_join_activation_failure_stays_on_host_path_exactly():
    """device.activate=fail:1 fires at device-join activation. The
    join must stay on the retained host reference path — identical
    results — instead of dying, and count the degradation."""
    sql = ("SELECT l.k, COUNT(*) AS c FROM l INNER JOIN r "
           "WITHIN (INTERVAL 5 SECOND) ON l.k = r.k "
           "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    batches = [
        ([{"k": "a", "x": 1.0}], [BASE], "l"),
        ([{"k": "a", "y": 2.0}], [BASE + 1000], "r"),
        ([{"k": "b", "x": 1.0}], [BASE + 2000], "l"),
        ([{"k": "b", "y": 4.0}], [BASE + 2500], "r"),
        ([{"k": "a", "x": 3.0}], [BASE + 30_000], "l"),
    ]
    sample = batches[0][0]
    ref, want = _feed(sql, batches, sample)
    FAULTS.arm("device.activate", "fail:1")
    ex, got = _feed(sql, batches, sample)
    FAULTS.disarm()
    assert _norm(got) == _norm(want)
    assert ex.device_fallbacks == 1
    assert ex.use_device_join is False
    assert ex._dev is None
    assert any(r.get("c") == 1 for r in got)  # the joins happened


# ---- device sessions: failure degrades to the host reference (ISSUE 10) ----


def _session_flow(sql_stream, view, stub, ctx, arm=None):
    """Shared session scenario: session-window COUNT/SUM per user, a
    batch extending sessions across micro-batches, then a far-future
    closer. Returns the closed-session rows."""
    stub.CreateStream(pb.Stream(stream_name=sql_stream))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text=f"CREATE VIEW {view} AS SELECT user, COUNT(*) AS c, "
                  f"SUM(v) AS s FROM {sql_stream} GROUP BY user, "
                  "SESSION (INTERVAL 2 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    qid = f"view-{view}"
    wait_attached(ctx, qid)
    if arm is not None:
        arm()
    append_rows(stub, sql_stream,
                [{"user": "a", "v": 1.0}, {"user": "a", "v": 2.0},
                 {"user": "b", "v": 5.0}],
                [BASE, BASE + 500, BASE + 700])
    # extends a's session cross-batch; b gets a second session later
    append_rows(stub, sql_stream,
                [{"user": "a", "v": 3.0}, {"user": "b", "v": 7.0}],
                [BASE + 1500, BASE + 9000])
    append_rows(stub, sql_stream, [{"user": "z", "v": 0.0}],
                [BASE + 60_000])
    rows = _poll_view(
        stub, view,
        lambda rs: any(r.get("user") == "b"
                       and r.get("winStart") == BASE + 9000
                       for r in rs))
    return qid, _norm([r for r in rows if r.get("user") != "z"])


def test_session_device_dispatch_failure_degrades_exactly():
    """device.session.dispatch=fail:1 fires inside the first session
    step dispatch. The executor must pull its state back to the host
    reference engine — identical closed rows, query alive — and the
    degradation must land in the device_path_fallbacks counter."""
    server, ctx, stub, channel = _serve()
    try:
        _, want = _session_flow("ss0", "sv0", stub, ctx)
    finally:
        channel.close(); server.stop(grace=1); ctx.shutdown()
    assert want  # the reference run closed real sessions

    server, ctx, stub, channel = _serve()
    try:
        qid, got = _session_flow(
            "ss1", "sv1", stub, ctx,
            arm=lambda: ctx.faults.arm("device.session.dispatch",
                                       "fail:1"))
        assert got == want
        task = ctx.running_queries[qid]
        ex = task.executor
        assert ex.device_fallbacks == 1
        assert ex.use_device_sessions is False and ex._dev is None
        # the task mirrored the degradation into the counter
        task._note_device_fallbacks()
        assert ctx.stats.stream_stat_get(
            "device_path_fallbacks", "ss1") == 1
        assert "fault_injected" in _event_kinds(ctx)
        # degraded, not dead: the query is still RUNNING
        assert ctx.persistence.get_query(qid).status == \
            TaskStatus.RUNNING
    finally:
        channel.close(); server.stop(grace=1); ctx.shutdown()


def test_session_device_activation_failure_stays_on_host_exactly():
    """device.session.activate=fail:1 fires at arena activation: the
    executor never migrates, stays on the host engine, and results are
    identical (engine-level twin of the server scenario above)."""
    from hstream_tpu.engine import ColumnType, Schema
    from hstream_tpu.engine.expr import Col
    from hstream_tpu.engine.plan import (
        AggKind,
        AggregateNode,
        AggSpec,
        SourceNode,
    )
    from hstream_tpu.engine.session import SessionExecutor
    from hstream_tpu.engine.window import SessionWindow

    schema = Schema.of(user=ColumnType.STRING, v=ColumnType.FLOAT)
    batches = [
        ([{"user": "a", "v": 1.0}, {"user": "b", "v": 2.0}],
         [BASE, BASE + 500]),
        ([{"user": "a", "v": 3.0}], [BASE + 1500]),
        ([{"user": "z", "v": 0.0}], [BASE + 60_000]),
    ]

    def run(fault):
        node = AggregateNode(
            child=SourceNode("s", schema), group_keys=[Col("user")],
            window=SessionWindow(2000, grace_ms=0),
            aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
                  AggSpec(AggKind.SUM, "s", input=Col("v"))])
        ex = SessionExecutor(node, schema, emit_changes=False)
        if fault:
            FAULTS.arm("device.session.activate", "fail:1")
        out = []
        for rows, ts in batches:
            out.extend(ex.process(rows, ts))
        FAULTS.disarm()
        return ex, list(out)

    ref, want = run(fault=False)
    assert ref._dev is not None  # the reference actually ran on device
    ex, got = run(fault=True)
    assert _norm(got) == _norm(want)
    assert ex.device_fallbacks == 1
    assert ex.use_device_sessions is False and ex._dev is None
    assert len(want) > 0


def _health_verdict(stub, qid):
    import json as _json

    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command="health", args=rec.dict_to_struct({"query": qid})))
    return _json.loads(resp.result)


def test_health_plane_ok_degraded_ok_across_session_fault():
    """ISSUE 13 satellite: the health endpoint tracks a seeded
    device.session.dispatch fault end to end — OK while the device
    path is healthy, DEGRADED (reason device_fallback) once the
    injected dispatch failure degrades the query to the host engine,
    and OK again after the operator clears the fault and restarts the
    query (fresh executor, device path re-activates)."""
    server, ctx, stub, channel = _serve()
    try:
        qid, got = _session_flow("hps", "hpv", stub, ctx)
        assert got  # real sessions closed — the query is doing work
        h = _health_verdict(stub, qid)
        assert h["verdict"] == "OK" and h["reasons"] == [], h
        assert h["device_fallbacks"] == 0

        # inject: the NEXT session step dispatch fails once -> the
        # executor pulls back to the host engine (degrade, not die)
        ctx.faults.arm("device.session.dispatch", "fail:1")
        append_rows(stub, "hps", [{"user": "q", "v": 1.0}],
                    [BASE + 120_000])
        assert _wait(lambda: _health_verdict(
            stub, qid)["verdict"] == "DEGRADED")
        h = _health_verdict(stub, qid)
        assert "device_fallback" in h["reasons"], h
        assert h["level"] == 1 and h["device_fallbacks"] == 1
        # the verdict gauge mirrors it for scrapers/the placer
        assert ctx.stats.gauges_snapshot()[
            ("query_health_level", qid)] == 1.0
        # degraded, not dead — still RUNNING on the host path
        assert ctx.persistence.get_query(qid).status == \
            TaskStatus.RUNNING

        # recover: clear the fault, operator restart -> fresh executor
        # re-activates the device path -> OK
        ctx.faults.disarm()
        stub.TerminateQueries(pb.TerminateQueriesRequest(
            query_ids=[qid]))
        stub.RestartQuery(pb.RestartQueryRequest(id=qid))
        wait_attached(ctx, qid)
        append_rows(stub, "hps", [{"user": "r", "v": 2.0}],
                    [BASE + 180_000])
        assert _wait(lambda: _health_verdict(
            stub, qid)["verdict"] == "OK")
        h = _health_verdict(stub, qid)
        assert h["device_fallbacks"] == 0, h
    finally:
        channel.close(); server.stop(grace=1); ctx.shutdown()


# ---- the registry itself: determinism + hot-path discipline -----------------


def test_registry_fail_nth_is_exact():
    reg = FaultRegistry()
    reg.arm("x", "fail:3:2")
    hits = []
    for i in range(1, 7):
        try:
            reg.point("x")
            hits.append(i)
        except InjectedFault as e:
            assert e.site == "x" and e.hit == i
    assert hits == [1, 2, 5, 6]  # fired on 3 and 4 exactly


def test_registry_prob_schedule_replays_with_seed():
    def pattern(seed):
        reg = FaultRegistry()
        reg.arm("x", f"prob:0.3:{seed}")
        out = []
        for _ in range(50):
            try:
                reg.point("x")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b = pattern(42), pattern(42)
    assert a == b  # same seed, same injections
    assert pattern(7) != a  # and the seed matters
    assert 0 < sum(a) < 50


def test_registry_torn_cut_is_seeded():
    def cut(seed):
        reg = FaultRegistry()
        reg.arm("x", f"torn:2:{seed}")
        data = bytes(range(200))
        assert reg.mutate("x", data) == data  # hit 1 passes through
        return reg.mutate("x", data)          # hit 2 is the tear

    torn_a, torn_b = cut(9), cut(9)
    assert torn_a == torn_b
    data = bytes(range(200))
    assert torn_a != data and data.startswith(torn_a)
    assert len(data) // 4 <= len(torn_a) < (3 * len(data)) // 4


def test_registry_point_and_mutate_hits_do_not_blend():
    """A site can host both probe kinds; torn schedules must only
    advance on mutate() so point() traffic cannot eat the tear."""
    reg = FaultRegistry()
    reg.arm("x", "torn:1:3")
    for _ in range(5):
        reg.point("x")  # must not consume the torn hit
    assert reg.mutate("x", b"0123456789abcdef") != b"0123456789abcdef"


def test_registry_inactive_is_identity_and_env_parses():
    reg = FaultRegistry()
    assert reg.active is False
    reg.point("anything")            # no-op, no raise
    assert reg.mutate("anything", b"data") == b"data"
    n = reg.load_env("a.b=fail:1; c.d=prob:0.5:3 ;bogus=nope:1;")
    assert n == 2  # malformed entry skipped loudly, not fatal
    assert set(reg.status()) == {"a.b", "c.d"}
    reg.disarm("a.b")
    assert set(reg.status()) == {"c.d"}
    reg.disarm()
    assert reg.active is False
    with pytest.raises(ValueError):
        reg.arm("x", "prob:1.5")
    with pytest.raises(ValueError):
        reg.arm("x", "fail")


# ---- multi-node failover (ISSUE 9): epoch fencing, promotion, dedup ---------


import random
import socket

from hstream_tpu.client.retry import RetryPolicy
from hstream_tpu.store import open_store
from hstream_tpu.store.replica import (
    OPLOG_ID,
    ReplicatedStore,
    promote_best,
    seal_replicas,
    serve_follower,
)
from hstream_tpu.store.api import DataBatch


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _log_contents(store, logid):
    tail = store.tail_lsn(logid)
    if tail == 0:
        return []
    r = store.new_reader()
    r.set_timeout(0)
    r.start_reading(logid, 1, tail)
    out = []
    while True:
        items = r.read(512)
        if not items:
            break
        for it in items:
            if isinstance(it, DataBatch):
                out.append((it.lsn, tuple(it.payloads)))
    return out


def _store_fingerprint(store):
    """Byte-level identity of a replica's REPLICATED state: every data
    log's full contents plus every meta key except the replica-local
    leadership binding (each node records its own epoch/role/node id).
    Two converged replicas must compare equal on this."""
    logs = {lid: _log_contents(store, lid) for lid in store.list_logs()
            if lid != OPLOG_ID}
    meta = {}
    for key in store.meta_list(""):
        if key.startswith("replica/"):
            continue
        meta[key] = store.meta_get(key)
    return {"logs": logs, "meta": meta}


class _ReplicaGroup:
    """One leader SQL server over a mem store + N in-process follower
    replica services, with teardown that survives partial failover."""

    def __init__(self, n_followers=2, ack_timeout_ms=2000):
        self.followers = []
        for i in range(n_followers):
            st = open_store("mem://")
            port = _free_port()
            addr = f"127.0.0.1:{port}"
            srv, svc = serve_follower(st, addr, node_id=f"replica-{i}")
            self.followers.append(
                {"store": st, "srv": srv, "svc": svc, "addr": addr})
        self.server, self.ctx = serve(
            "127.0.0.1", 0, "mem://",
            replicate=",".join(f["addr"] for f in self.followers),
            replication_factor=1 + n_followers,
            replica_ack_timeout_ms=ack_timeout_ms)
        self.addr = f"127.0.0.1:{self.ctx.port}"
        self.channel = grpc.insecure_channel(self.addr)
        self.stub = HStreamApiStub(self.channel)
        # set when a follower is re-served as the new leader
        self.new_server = None
        self.new_ctx = None

    def follower(self, addr):
        return next(f for f in self.followers if f["addr"] == addr)

    def caught_up(self):
        seq = self.ctx.store.oplog_seq
        return all(f["svc"].applied_seq >= seq for f in self.followers)

    def close(self):
        self.channel.close()
        self.server.stop(grace=1)
        try:
            self.ctx.shutdown()
        except Exception:  # noqa: BLE001 — a fenced store refuses the
            pass           # final status writes; teardown must go on
        if self.new_server is not None:
            self.new_server.stop(grace=1)
            try:
                self.new_ctx.shutdown()
            except Exception:  # noqa: BLE001
                pass
        for f in self.followers:
            f["svc"].close()
            f["srv"].stop(grace=1)


class _Producer:
    """Append client with a stamped (producer_id, seq) and a retry
    policy that follows NOT_LEADER hints by rebinding its channel —
    the failover-aware client contract, driven raw for determinism."""

    def __init__(self, addr, producer_id="prod-1", seed=7):
        self.addr = addr
        self.producer_id = producer_id
        self.channel = grpc.insecure_channel(addr)
        self.stub = HStreamApiStub(self.channel)
        self.policy = RetryPolicy(attempts=6, base_ms=5,
                                  rng=random.Random(seed))

    def _follow(self, hint):
        old = self.channel
        self.addr = hint
        self.channel = grpc.insecure_channel(hint)
        self.stub = HStreamApiStub(self.channel)
        old.close()

    def append(self, stream, row, seq):
        req = pb.AppendRequest(stream_name=stream,
                               producer_id=self.producer_id,
                               producer_seq=seq)
        req.records.append(rec.build_record(row, publish_time_ms=BASE))

        def attempt(r):
            return self.stub.Append(r)

        return self.policy.call(attempt, req,
                                on_leader_hint=self._follow)

    def close(self):
        self.channel.close()


def test_leader_failover_retrying_producer_exact_once():
    """THE ISSUE 9 acceptance scenario: the leader loses leadership
    mid-append-stream (a follower is promoted out from under it), the
    retrying producer follows the NOT_LEADER hint to the new leader,
    the retry that straddles the promotion lands EXACTLY once, and the
    surviving replicas converge byte-identical."""
    g = _ReplicaGroup(n_followers=2)
    prod = _Producer(g.addr)
    try:
        g.stub.CreateStream(pb.Stream(stream_name="fo1"))
        lsns = {}
        for seq in (1, 2, 3):
            resp = prod.append("fo1", {"n": seq}, seq)
            assert not resp.duplicate
            lsns[seq] = resp.record_ids[0].batch_id
        assert _wait(g.caught_up), "followers never caught up"

        # leadership moves: promote the most-caught-up follower, with
        # the hint naming the NEW SQL server we boot over its store
        new_port = _free_port()
        promo = promote_best([f["addr"] for f in g.followers],
                             leader_addr=f"127.0.0.1:{new_port}")
        assert promo["ok"] and promo["epoch"] == 1  # 0 everywhere + 1
        # most-caught-up rule: equal (epoch, applied_seq) -> highest
        # node id wins the tiebreak
        assert promo["node_id"] == "replica-1"
        # the OTHER follower was sealed at the new epoch immediately
        other = next(f for f in g.followers
                     if f["addr"] != promo["target"])
        assert promo["sealed"] == [other["addr"]]
        assert other["svc"].epoch == promo["epoch"]

        winner = g.follower(promo["target"])
        g.new_server, g.new_ctx = serve(
            "127.0.0.1", new_port, store=winner["store"],
            replicate=other["addr"], replication_factor=2,
            replica_ack_timeout_ms=2000)
        assert g.new_ctx.store.epoch == promo["epoch"]
        assert g.new_ctx.store.node_id == "replica-1"

        # the old leader discovers the fence on its next contact
        assert _wait(lambda: g.ctx.store.fenced_by is not None,
                     timeout=15), "old leader never fenced"
        assert g.ctx.store.fenced_by[0] == promo["epoch"]

        # the producer retries seq=3 (its ack raced the failover) and
        # continues with 4..5: attempt 1 hits the fenced leader, gets
        # NOT_LEADER + hint, follows it — exactly-once throughout
        r3 = prod.append("fo1", {"n": 3}, 3)
        assert prod.policy.leader_follows >= 1
        assert prod.addr == f"127.0.0.1:{new_port}"
        assert r3.duplicate, "retry across failover must dedup"
        assert r3.record_ids[0].batch_id == lsns[3]
        for seq in (4, 5):
            resp = prod.append("fo1", {"n": seq}, seq)
            assert not resp.duplicate
            lsns[seq] = resp.record_ids[0].batch_id

        # survivors converge byte-identical, with exactly 5 batches
        new_store = g.new_ctx.store
        assert _wait(lambda: other["svc"].applied_seq
                     >= new_store.oplog_seq), "peer never converged"
        logid = g.new_ctx.streams.get_logid("fo1")
        want = _log_contents(new_store.local, logid)
        assert len(want) == 5 and want[-1][0] == lsns[5]
        assert _log_contents(other["store"], logid) == want
        assert _store_fingerprint(other["store"]) == \
            _store_fingerprint(new_store.local)

        # observability: the dedup answered append is counted, the old
        # leader journals its fencing, epoch/dedup gauges render
        assert g.new_ctx.stats.stream_stat_get("append_deduped",
                                               "fo1") == 1
        assert g.ctx.store.fenced_appends >= 1
        assert "replica_fenced" in _event_kinds(g.ctx)
        from hstream_tpu.stats.prometheus import render_metrics

        text = render_metrics(g.new_ctx)
        assert f"hstream_replica_epoch {promo['epoch']}" in text
        assert "hstream_dedup_window_size 5" in text
        assert 'hstream_append_deduped_total{stream="fo1"} 1' in text
    finally:
        prod.close()
        g.close()


def test_stale_leader_partition_appends_fenced_not_replicated():
    """replica.partition drops every Replicate: the partitioned
    leader's appends land only on its own store (honestly degraded).
    A follower promoted during the partition fences it — its
    post-fence appends are REJECTED, the orphan entry never reaches a
    survivor, and a raw stale-epoch Replicate is answered fenced."""
    g = _ReplicaGroup(n_followers=2, ack_timeout_ms=600)
    try:
        g.stub.CreateStream(pb.Stream(stream_name="pt1"))
        req = pb.AppendRequest(stream_name="pt1")
        req.records.append(rec.build_record({"n": 1},
                                            publish_time_ms=BASE))
        g.stub.Append(req)
        assert _wait(g.caught_up)
        logid = g.ctx.streams.get_logid("pt1")

        # partition: every leader->follower Replicate now fails
        FAULTS.arm("replica.partition", "fail:1:100000")
        req = pb.AppendRequest(stream_name="pt1")
        req.records.append(rec.build_record({"n": "orphan"},
                                            publish_time_ms=BASE))
        g.stub.Append(req)  # degraded ack: landed on the leader only
        assert g.ctx.store.last_ack_status.startswith("degraded")

        # promotion while partitioned: Promote is a different RPC, so
        # the operator can still move leadership; the seal RPCs ride
        # Replicate and are blocked — best-effort, reported as such
        promo = promote_best([f["addr"] for f in g.followers],
                             leader_addr="127.0.0.1:1")
        assert promo["ok"] and promo["sealed"] == []
        FAULTS.disarm("replica.partition")
        # operator retries the seal once the link heals
        other = next(f for f in g.followers
                     if f["addr"] != promo["target"])
        assert seal_replicas([other["addr"]], epoch=promo["epoch"],
                             leader_id=promo["node_id"],
                             leader_hint="127.0.0.1:1") == \
            [other["addr"]]

        assert _wait(lambda: g.ctx.store.fenced_by is not None,
                     timeout=20), "stale leader never fenced"
        # post-fence appends are refused with the hint, not stored
        tail_before = g.ctx.store.local.tail_lsn(logid)
        req = pb.AppendRequest(stream_name="pt1")
        req.records.append(rec.build_record({"n": "rejected"},
                                            publish_time_ms=BASE))
        try:
            g.stub.Append(req)
            raise AssertionError("fenced leader accepted an append")
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.UNAVAILABLE
            assert "not_leader leader_hint=127.0.0.1:1" in e.details()
            md = dict(e.trailing_metadata() or ())
            assert md.get("x-leader-hint") == "127.0.0.1:1"
        assert g.ctx.store.local.tail_lsn(logid) == tail_before

        # neither survivor ever saw the orphan or the rejected append
        for f in g.followers:
            assert len(_log_contents(f["store"], logid)) == 1
        # and a stale-epoch Replicate is fenced explicitly, with the
        # hint pointing at the promotion's leader_addr
        with grpc.insecure_channel(other["addr"]) as ch:
            from hstream_tpu.proto.rpc import StoreReplicaStub

            resp = StoreReplicaStub(ch).Replicate(
                pb.ReplicateRequest(
                    entries=[pb.LogEntry(seq=99, op=pb.OP_CREATE_LOG,
                                         logid=77)],
                    leader_id=g.ctx.store.node_id, epoch=0),
                timeout=5)
        assert resp.fenced and resp.epoch == promo["epoch"]
        assert resp.leader_hint == "127.0.0.1:1"
        assert not other["store"].log_exists(77)
        assert g.ctx.store.fenced_appends >= 1
        assert "replica_fenced" in _event_kinds(g.ctx)
    finally:
        g.close()


def test_dueling_promotions_resolve_to_one_leader():
    """Two operators promote two followers at the SAME epoch (the
    promote.race window, widened by the armed delay site). First
    contact resolves deterministically — the lexicographically higher
    node id keeps leadership, the other demotes and follows — so the
    group can never run two same-epoch leaders."""
    g = _ReplicaGroup(n_followers=2)
    try:
        assert _wait(g.caught_up)
        FAULTS.arm("replica.promote.race", "delay:30")
        from hstream_tpu.proto.rpc import StoreReplicaStub

        # both promotions race to epoch 1 and both "succeed"
        for f in g.followers:
            with grpc.insecure_channel(f["addr"]) as ch:
                resp = StoreReplicaStub(ch).Promote(
                    pb.PromoteRequest(epoch=1, leader_addr=f["addr"],
                                      promoted_by="race"),
                    timeout=5)
            assert resp.ok
        FAULTS.disarm("replica.promote.race")
        lo, hi = g.followers[0], g.followers[1]  # replica-0 < replica-1
        assert lo["svc"].is_leader and hi["svc"].is_leader

        # first contact between the duelists: the seal each new leader
        # sends carries (epoch, node_id); the lower id must stand down
        assert seal_replicas([lo["addr"]], epoch=1,
                             leader_id=hi["svc"].node_id,
                             leader_hint=hi["addr"]) == [lo["addr"]]
        assert not lo["svc"].is_leader
        assert lo["store"].meta_get("replica/leader_id") == \
            hi["svc"].node_id.encode()
        # ... and the loser's own seal bounces off the winner
        with grpc.insecure_channel(hi["addr"]) as ch:
            resp = StoreReplicaStub(ch).Replicate(
                pb.ReplicateRequest(entries=[], epoch=1,
                                    leader_id=lo["svc"].node_id,
                                    leader_hint=lo["addr"]),
                timeout=5)
        assert resp.fenced
        assert hi["svc"].is_leader
        assert [f["svc"].is_leader for f in g.followers] == [False, True]
    finally:
        g.close()


def test_follower_divergence_guard_halts_loudly():
    """ISSUE 9 satellite: a follower whose local store drifted from
    the op-log (its data log was corrupted out-of-band) must HALT with
    the divergence error — refusing every further entry, applying
    nothing, never growing the corrupt log — instead of drifting."""
    g = _ReplicaGroup(n_followers=2)
    try:
        g.stub.CreateStream(pb.Stream(stream_name="dv1"))
        for n in (1, 2):
            req = pb.AppendRequest(stream_name="dv1")
            req.records.append(rec.build_record({"n": n},
                                                publish_time_ms=BASE))
            g.stub.Append(req)
        assert _wait(g.caught_up)
        logid = g.ctx.streams.get_logid("dv1")
        bad, good = g.followers[0], g.followers[1]

        # corrupt ONE follower: its data log loses its records, so the
        # next replicated append expects lsn 3 over a tail of 0
        bad["store"].remove_log(logid)
        bad["store"].create_log(logid)
        frozen_seq = bad["svc"].applied_seq
        req = pb.AppendRequest(stream_name="dv1")
        req.records.append(rec.build_record({"n": 3},
                                            publish_time_ms=BASE))
        g.stub.Append(req)  # acked by the good follower

        # the corrupt follower halted: applied_seq frozen, nothing
        # landed in the recreated log, and it now refuses EVERYTHING
        assert _wait(lambda: bad["svc"]._broken is not None,
                     timeout=15), "divergence never latched"
        assert "diverged" in str(bad["svc"]._broken)
        assert bad["svc"].applied_seq == frozen_seq
        assert _log_contents(bad["store"], logid) == []
        from hstream_tpu.proto.rpc import StoreReplicaStub

        with grpc.insecure_channel(bad["addr"]) as ch:
            try:
                StoreReplicaStub(ch).Replicate(
                    pb.ReplicateRequest(
                        entries=[], leader_id=g.ctx.store.node_id,
                        epoch=0),
                    timeout=5)
                raise AssertionError("diverged replica accepted entries")
            except grpc.RpcError as e:
                assert e.code() == grpc.StatusCode.INTERNAL
                assert "diverged" in (e.details() or "")
        # the healthy follower carried on: all three records applied
        assert _wait(lambda: good["svc"].applied_seq
                     >= g.ctx.store.oplog_seq)
        assert len(_log_contents(good["store"], logid)) == 3
    finally:
        g.close()


def test_heartbeat_loss_triggers_lease_auto_promotion():
    """replica.heartbeat.drop kills every idle-leader heartbeat: the
    flag-gated lease monitor on the follower must promote it once the
    leader goes silent past the lease, and the old leader must fence
    itself on the next contact — leadership heals without an
    operator."""
    st = open_store("mem://")
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    fsrv, svc = serve_follower(st, addr, node_id="auto-f",
                               lease_timeout_s=0.6)
    leader = ReplicatedStore(open_store("mem://"), [addr],
                             replication_factor=2, client_addr="old:1")
    try:
        leader.create_log(5)
        leader.append(5, b"one")
        assert _wait(lambda: svc.applied_seq >= leader.oplog_seq)
        # heartbeats now die leader-side; the follower's lease expires
        FAULTS.arm("replica.heartbeat.drop", "fail:1:100000")
        assert _wait(lambda: svc.is_leader, timeout=20), \
            "lease auto-promotion never fired"
        assert svc.epoch >= 1
        assert _wait(lambda: leader.fenced_by is not None, timeout=20)
        assert leader.fenced_by[1] == addr  # hint = promoted follower
        try:
            leader.append(5, b"two")
            raise AssertionError("fenced leader accepted an append")
        except Exception as e:  # noqa: BLE001 — typed check below
            from hstream_tpu.common.errors import NotLeaderError

            assert isinstance(e, NotLeaderError)
            assert e.leader_hint == addr
    finally:
        FAULTS.disarm()
        leader.close()
        svc.close()
        fsrv.stop(grace=1)


def test_registry_delay_schedule_sleeps_only_scheduled_hit():
    reg = FaultRegistry()
    reg.arm("x", "delay:40:2")
    t0 = time.perf_counter()
    reg.point("x")  # hit 1: no delay
    assert time.perf_counter() - t0 < 0.03
    t0 = time.perf_counter()
    reg.point("x")  # hit 2: ~40ms
    assert time.perf_counter() - t0 >= 0.035
    assert reg.status()["x"]["injected"] == 1


# ---- seeded interleaving perturbation (ISSUE 14) ---------------------------
#
# The lock-order witness armed + yield: schedules at the traced
# lock.acquire.* sites: each scenario replays a documented race family
# under K seeds, asserting (a) the witness reports ZERO lock-order
# cycles, and (b) the subsystem's exact-result contract holds under
# every explored interleaving. A failing seed replays identically.

from hstream_tpu.common.locktrace import LOCKTRACE

INTERLEAVE_SEEDS = (3, 17, 101)


def _arm_yields(sites, seed, n=2):
    for site in sites:
        FAULTS.arm(f"lock.acquire.{site}", f"yield:{n}:{seed}")


def test_interleaving_appendfront_submit_vs_close_races():
    """Submitters racing close() across lanes: every submitted future
    settles (an accepted batch lands durably IN ORDER, a refused
    submit raises the closed error), nothing hangs, and the armed
    witness sees no lock-order cycle — under every seed."""
    from hstream_tpu.server.appendfront import AppendFront
    from hstream_tpu.store.memstore import MemLogStore

    for seed in INTERLEAVE_SEEDS:
        FAULTS.disarm()
        LOCKTRACE.disarm()
        LOCKTRACE.arm()
        _arm_yields(("appendfront.lane", "appendfront.submit",
                     "appendfront.stat"), seed)
        store = MemLogStore()
        for logid in (1, 2, 3, 4):
            store.create_log(logid)
        front = AppendFront(store, lanes=2)
        results: dict[int, list] = {t: [] for t in range(4)}

        def producer(tid):
            for i in range(25):
                payload = b"%d:%d" % (tid, i)
                try:
                    fut = front.submit(1 + tid, [payload])
                except RuntimeError:
                    results[tid].append(("refused", payload))
                    continue
                try:
                    lsn = fut.result(timeout=10)
                    results[tid].append(("ok", payload, lsn))
                except Exception:  # noqa: BLE001 — racing close()
                    results[tid].append(("failed", payload))

        threads = [__import__("threading").Thread(
            target=producer, args=(t,)) for t in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        front.close(timeout=10)
        for t in threads:
            t.join(timeout=15)
            assert not t.is_alive(), f"seed {seed}: producer hung"
        st = front.stats()
        assert st["in_flight"] == 0, \
            f"seed {seed}: unresolved futures ({st})"
        for tid in range(4):
            accepted = [r for r in results[tid] if r[0] == "ok"]
            # every future settled one way or the other
            assert len(results[tid]) == 25
            assert not any(r[0] == "failed" for r in results[tid]), \
                f"seed {seed}: a submitted future errored ({results[tid]})"
            # durable, exactly the accepted payloads, in submit order
            landed = [p for _lsn, ps in _log_contents(store, 1 + tid)
                      for p in ps]
            assert landed == [r[1] for r in accepted], \
                f"seed {seed}: lane {tid} order/contents diverged"
        assert LOCKTRACE.cycles() == [], \
            f"seed {seed}: witness reported a lock-order cycle"
        LOCKTRACE.disarm()
        FAULTS.disarm()


def test_interleaving_supervisor_restart_vs_cancel_races():
    """note_death racing cancel(): after cancel() returns there is no
    pending or in-flight restart left for the query and no resurrect
    can land later — under every seed, witness armed, yields at the
    supervisor lock."""
    import threading as _threading

    from hstream_tpu.server.persistence import QueryInfo
    from hstream_tpu.server.scheduler import QuerySupervisor

    class _Persist:
        def get_query(self, qid):
            return QueryInfo(qid, "select 1", 0)

        def set_query_status(self, qid, status):
            pass

    class _Ctx:
        def __init__(self):
            self.running_queries = {}
            self.persistence = _Persist()

    for seed in INTERLEAVE_SEEDS:
        FAULTS.disarm()
        LOCKTRACE.disarm()
        LOCKTRACE.arm()
        _arm_yields(("scheduler.supervisor",), seed)
        ctx = _Ctx()
        resumed = []
        sup = QuerySupervisor(ctx, resume_fn=resumed.append, seed=seed)
        sup.BACKOFF_BASE_S = 0.001
        info = QueryInfo("q-race", "select 1", 0)
        try:
            for round_ in range(8):
                sup.note_death(info, RuntimeError(f"death {round_}"))
                canceller = _threading.Thread(
                    target=sup.cancel, args=("q-race",))
                canceller.start()
                canceller.join(timeout=35)
                assert not canceller.is_alive(), \
                    f"seed {seed}: cancel() hung"
                st = sup.status()
                assert "q-race" not in st["pending"], \
                    f"seed {seed}: pending restart survived cancel"
                n_after_cancel = len(resumed)
                time.sleep(0.02)
                assert len(resumed) == n_after_cancel, \
                    f"seed {seed}: a restart resurrected after cancel"
        finally:
            sup.shutdown()
        assert LOCKTRACE.cycles() == [], \
            f"seed {seed}: witness reported a lock-order cycle"
        LOCKTRACE.disarm()
        FAULTS.disarm()


def test_interleaving_promotion_vs_append_races():
    """A producer appending through the leader store while a follower
    is promoted out from under it: every append either lands durably
    on the promoted side exactly once or raises the typed NotLeader
    refusal — never both, never lost after ack — and the armed
    witness sees no replica lock-order cycle."""
    from hstream_tpu.common.errors import NotLeaderError

    for seed in INTERLEAVE_SEEDS[:2]:  # two seeds keep CI < 30s
        FAULTS.disarm()
        LOCKTRACE.disarm()
        LOCKTRACE.arm()
        _arm_yields(("replica.oplog", "replica.follower"), seed)
        follower_store = open_store("mem://")
        port = _free_port()
        fsrv, svc = serve_follower(follower_store, f"127.0.0.1:{port}",
                                   node_id="replica-p")
        leader = ReplicatedStore(open_store("mem://"),
                                 [f"127.0.0.1:{port}"],
                                 replication_factor=2,
                                 ack_timeout_s=2.0)
        try:
            leader.create_log(7)
            acked: list[tuple[int, bytes, str]] = []
            refused = []

            def producer():
                for i in range(40):
                    payload = b"row-%d" % i
                    try:
                        lsn = leader.append_batch(7, [payload])
                        # single appender: last_ack_status is ours.
                        # An append racing the fence acks DEGRADED
                        # (journaled, observable) — only a fully
                        # "replicated" ack promises follower
                        # durability (the ISSUE 9 contract)
                        acked.append((lsn, payload,
                                      leader.last_ack_status))
                    except NotLeaderError:
                        refused.append(payload)
                        return
                    except Exception:  # noqa: BLE001 — a replicate
                        # racing the fence can surface as a transport
                        # error; the contract below only binds ACKED
                        refused.append(payload)
                        return

            t = __import__("threading").Thread(target=producer)
            t.start()
            time.sleep(0.02)
            # promotion out from under the producer
            promo = promote_best([f"127.0.0.1:{port}"],
                                 leader_addr="127.0.0.1:1")
            assert promo["ok"], promo
            t.join(timeout=30)
            assert not t.is_alive(), f"seed {seed}: producer hung"
            # every ACKED append is durable on the promoted follower
            _wait(lambda: svc.applied_seq >= leader.oplog_seq
                  or leader.fenced_by is not None, timeout=10)
            landed = dict(_log_contents(follower_store, 7))
            replicated = [(lsn, p) for lsn, p, st in acked
                          if st == "replicated"]
            assert replicated, f"seed {seed}: nothing replicated " \
                               f"before the fence — scenario degenerate"
            for lsn, payload in replicated:
                assert landed.get(lsn) == (payload,), \
                    f"seed {seed}: acked lsn {lsn} missing/diverged"
            # the fence window is honest: anything acked after the
            # promotion was marked degraded, never silently clean
            if leader.fenced_by is not None and len(replicated) < \
                    len(acked):
                assert any(st != "replicated"
                           for _l, _p, st in acked)
        finally:
            leader.close()
            svc.close()
            fsrv.stop(grace=1)
        assert LOCKTRACE.cycles() == [], \
            f"seed {seed}: witness reported a lock-order cycle"
        LOCKTRACE.disarm()
        FAULTS.disarm()


# ---- the placer (ISSUE 17): kill-the-owner adoption, exact results ----------


def _placer_cluster(n=3, *, lease_ms=800):
    """N armed servers over ONE shared mem store: every node runs a
    placer tick loop, heartbeats its owned queries, and sweeps for
    lapsed owners — the in-process stand-in for a real cluster."""
    store = open_store("mem://")
    nodes = []
    for _ in range(n):
        server, ctx = serve(
            "127.0.0.1", 0, store=store, owns_store=False,
            placer_interval_ms=100, heartbeat_lease_ms=lease_ms,
            snapshot_interval_ms=60, load_report_interval_ms=300)
        nodes.append((server, ctx))
    return store, nodes


def _placer_kill(server, ctx):
    """Crash-style death: no drop_assignment, no record cleanup — the
    node's scheduler records simply stop heartbeating, exactly like a
    SIGKILL'd process over a surviving shared store."""
    ctx.placer.stop()
    ctx.supervisor.shutdown()
    server.stop(grace=0)
    for task in list(ctx.running_queries.values()):
        try:
            task.stop(detach=True)
        except Exception:  # noqa: BLE001
            pass
    ctx.running_queries.clear()
    ctx.load_reporter.stop()


def _placer_owners(nodes, qid, dead):
    return [i for i, (_s, c) in enumerate(nodes)
            if i not in dead and qid in c.running_queries]


def _sink_final(rows, count_col):
    """Last-change-wins fold of an EMIT CHANGES sink log: the final
    count per (key, window). Replayed changes after a snapshot resume
    overwrite with identical values, so duplicates are invisible —
    LOST rows are not."""
    final = {}
    for r in rows:
        if "k" in r and count_col in r and "winStart" in r:
            final[(r["k"], r["winStart"])] = r[count_col]
    return final


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_placer_kill_owner_adoption_exact(seed):
    """Kill the node that owns a live query mid-stream: within the
    heartbeat lease + a few placer ticks EXACTLY ONE survivor adopts
    it (zero double-owners at every sampled instant), resumes from the
    snapshot, and the sink's final per-window counts equal a no-fault
    single-executor run over the identical row sequence."""
    rng = random.Random(seed)
    store, nodes = _placer_cluster(3, lease_ms=800)
    dead: set[int] = set()
    channels = []
    try:
        _s0, c0 = nodes[0]
        ch0 = grpc.insecure_channel(f"127.0.0.1:{c0.port}")
        channels.append(ch0)
        stub0 = HStreamApiStub(ch0)
        stub0.CreateStream(pb.Stream(stream_name="src"))
        stub0.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE STREAM snk AS SELECT k, COUNT(*) AS c "
                      "FROM src GROUP BY k, TUMBLING (INTERVAL 10 "
                      "SECOND) GRACE BY INTERVAL 0 SECOND "
                      "EMIT CHANGES;"))
        qid = c0.persistence.get_queries()[0].query_id
        assert _wait(lambda: len(_placer_owners(nodes, qid, dead)) == 1,
                     timeout=15), "query never landed on a node"

        batches = []  # the full seeded row sequence, for the reference

        def append_via(ctx, rows, ts):
            req = pb.AppendRequest(stream_name="src")
            for row, t in zip(rows, ts):
                req.records.append(
                    rec.build_record(row, publish_time_ms=t))
            ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
            channels.append(ch)
            HStreamApiStub(ch).Append(req)
            batches.append((rows, ts))

        def seeded_batch(w, n):
            rows = [{"k": rng.choice("abc"), "v": rng.randrange(10)}
                    for _ in range(n)]
            ts = [BASE + w * 10_000 + i for i in range(len(rows))]
            return rows, ts

        # stream a few windows at the initial owner
        for w in range(3):
            append_via(c0, *seeded_batch(w, rng.randrange(3, 7)))
        owner = _placer_owners(nodes, qid, dead)[0]
        sink_has_rows = lambda: bool(  # noqa: E731
            _sink_final(_read_chaos_sink(c0, "snk"), "c"))
        assert _wait(sink_has_rows, timeout=30), \
            "no output before the kill; scenario degenerate"

        # KILL the owner mid-stream
        _placer_kill(*nodes[owner])
        dead.add(owner)
        survivor_ctx = next(c for i, (_s, c) in enumerate(nodes)
                            if i not in dead)
        # rows keep arriving while the query is ownerless
        for w in range(3, 5):
            append_via(survivor_ctx, *seeded_batch(w, rng.randrange(3, 7)))

        # exactly one survivor adopts; zero double-owners at EVERY poll
        deadline = time.time() + 20
        adopted = False
        while time.time() < deadline:
            owners = _placer_owners(nodes, qid, dead)
            assert len(owners) <= 1, \
                f"seed {seed}: double owners {owners}"
            if owners and owners[0] != owner:
                adopted = True
                break
            time.sleep(0.05)
        assert adopted, f"seed {seed}: no survivor adopted {qid}"

        # drain the tail + close every window, then compare exactly
        for w in range(5, 7):
            append_via(survivor_ctx, *seeded_batch(w, rng.randrange(3, 7)))
        closer = ([{"k": "zz", "v": 0}], [BASE + 90_000])
        append_via(survivor_ctx, *closer)

        ref_ex, ref_rows = _feed(
            "SELECT k, COUNT(*) AS c FROM src GROUP BY k, "
            "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
            "EMIT CHANGES;",
            batches, sample=[{"k": "a", "v": 0}])
        want = _sink_final(ref_rows, "c")
        assert want, "reference emitted nothing; scenario degenerate"

        def exact():
            got = _sink_final(_read_chaos_sink(survivor_ctx, "snk"), "c")
            return all(got.get(kw) == c for kw, c in want.items())

        assert _wait(exact, timeout=30), (
            f"seed {seed}: adopted run diverged: "
            f"{_sink_final(_read_chaos_sink(survivor_ctx, 'snk'), 'c')}"
            f" != {want}")
        # the record names the adopter, owned, heartbeating
        from hstream_tpu.server import scheduler
        a = scheduler.assignment(survivor_ctx, qid)
        owner_idx = _placer_owners(nodes, qid, dead)[0]
        assert a["node"] == scheduler.node_name(nodes[owner_idx][1])
        assert a["state"] == "owned"
        assert scheduler.owner_live(a, lease_ms=5000)
        # ... and the adoption was journaled + counted
        kinds = [e["kind"] for e in nodes[owner_idx][1].events.query(
            kind="query_adopted", limit=10)]
        assert kinds, f"seed {seed}: no query_adopted event"
    finally:
        for ch in channels:
            ch.close()
        for i, (server, ctx) in enumerate(nodes):
            if i in dead:
                continue
            server.stop(grace=0.1)
            ctx.shutdown()
        store.close()


def _read_chaos_sink(ctx, stream):
    from hstream_tpu.common import columnar

    logid = ctx.streams.get_logid(stream)
    tail = ctx.store.tail_lsn(logid)
    out = []
    if not tail:
        return out
    r = ctx.store.new_reader()
    r.set_timeout(0)
    r.start_reading(logid, 1, tail)
    while True:
        items = r.read(256)
        if not items:
            break
        for it in items:
            if not isinstance(it, DataBatch):
                continue
            for p in it.payloads:
                pr = rec.parse_record(p)
                crows = columnar.payload_rows(pr.payload)
                if crows is not None:
                    out.extend(crows)
                    continue
                row = rec.record_to_dict(pr)
                if row is not None:
                    out.append(row)
    return out


# ---- protocheck counterexamples as chaos schedules (ISSUE 19) ---------------
#
# The model checker in tools/protocheck emits counterexamples as ACTION
# SCHEDULES — the same shape as the fault schedules above: a literal
# list of (action, node) steps anyone can replay. The schedules below
# were rendered from real mutation-gate counterexamples and are pinned
# here as chaos regressions: under the reverted fix the schedule
# reproduces the exact violation; on the LIVE tree the same schedule is
# clean. If a refactor re-introduces one of these bugs, the live half
# fails with a replayable script of the split-brain.

PROTOCHECK_SCHEDULES = [
    # reverting the fresh-lease refusal in try_adopt_live: one adopt
    # sweep steals a query whose owner heartbeated 0ms ago
    ("fresh-heartbeat-refusal", "kill-2",
     [("adopt", 0)], "seizure-fresh-lease", False),
    # reverting the 3x-interval lease clamp: after one crash and two
    # clock advances the survivor seizes a lease that SHOULD still be
    # live under the clamped bound
    ("lease-unclamped", "clamp-2",
     [("crash", 0), ("advance",), ("advance",), ("adopt", 1)],
     "seizure-fresh-lease", False),
    # reverting the CREATED-rescue in the adopt sweep: the offeree
    # crashes and the offered-but-never-launched query is stranded
    ("created-no-rescue", "created-2",
     [("crash", 1)], "convergence-offer", True),
]


@pytest.mark.parametrize(
    "mutant,scenario,schedule,rule,stabilized",
    PROTOCHECK_SCHEDULES, ids=[s[0] for s in PROTOCHECK_SCHEDULES])
def test_protocheck_schedule_replays_bug_and_live_fix(
        mutant, scenario, schedule, rule, stabilized):
    from tools.protocheck.explore import replay
    from tools.protocheck.model import SCENARIOS
    from tools.protocheck.mutants import BY_NAME

    m = BY_NAME[mutant]
    # under the reverted fix the schedule reproduces the violation,
    # deterministically (identical canonical state at every step)
    v1, k1, _ = replay(SCENARIOS[scenario], schedule, mutant=m,
                       stabilize=stabilized)
    v2, k2, _ = replay(SCENARIOS[scenario], schedule, mutant=m,
                       stabilize=stabilized)
    assert v1 and v1[0].rule == rule, (mutant, [str(v) for v in v1])
    assert k1 == k2
    # the live tree survives the exact same schedule
    v_live, _, _ = replay(SCENARIOS[scenario], schedule,
                          stabilize=stabilized)
    assert not v_live, (mutant, [str(v) for v in v_live])
