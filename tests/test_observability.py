"""Observability plane tests (ISSUE 3): /metrics exposition
correctness (golden file, label escaping, histogram bucket
monotonicity, naming), the event journal (ring bounding under
concurrent writers, admin verb, GET /events), request correlation
client -> gateway -> handler log record, and the registry lint."""

import io
import json
import logging
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import grpc
import pytest

from hstream_tpu.client import Client
from hstream_tpu.common.logger import current_request_id
from hstream_tpu.http_gateway import serve_gateway
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve
from hstream_tpu.stats import GAUGES, HISTOGRAMS, Histogram, StatsHolder
from hstream_tpu.stats.events import EventJournal
from hstream_tpu.stats.prometheus import (
    escape_label_value,
    render_holder,
    render_metrics,
)

BASE = 1_700_000_000_000
GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "metrics_golden.txt")


@pytest.fixture(scope="module")
def stack():
    # trace_sample=1.0: every stamped request records spans (ISSUE 13
    # roundtrip tests); everything else is unaffected
    server, ctx = serve("127.0.0.1", 0, "mem://", metrics_port=0,
                        trace_sample=1.0)
    addr = f"127.0.0.1:{ctx.port}"
    httpd, gw = serve_gateway(addr, port=0)
    http_base = f"http://127.0.0.1:{httpd.server_port}"
    channel = grpc.insecure_channel(addr)
    stub = HStreamApiStub(channel)
    yield addr, http_base, stub, ctx
    channel.close()
    httpd.shutdown()
    gw.close()
    server.stop(grace=1)
    ctx.shutdown()


def _http(method, base, path, body=None, headers=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(base + path, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req) as resp:
        return resp.status, resp.read(), dict(resp.headers)


# ---- StatsHolder registry semantics (satellite fixes) ----------------------


def test_peek_rate_unregistered_raises_like_ts():
    stats = StatsHolder()
    with pytest.raises(KeyError):
        stats.time_series_peek_rate("no_such_series", "s")
    with pytest.raises(KeyError):
        stats._ts("no_such_series", "s")
    # registered-but-unseen stream still peeks 0.0 without allocating
    assert stats.time_series_peek_rate("append_in_bytes", "s") == 0.0
    assert stats.time_series_streams("append_in_bytes") == []


def test_time_series_fixed_rings_stay_bounded():
    """The MultiLevelTimeSeries rings are fixed lists — adds move a
    cursor, never grow a dict — and an idle gap wider than a ring
    zeroes it instead of leaking stale buckets (exactness against
    brute-force recounts lives in tests/test_cluster_stats.py)."""
    from hstream_tpu.stats.timeseries import MultiLevelTimeSeries

    ts = MultiLevelTimeSeries()
    for i in range(300):
        ts.add(1.0, now=1000.0 + i)
    assert [lv.n for lv in ts.levels] == [60, 60, 60]
    # 1min level holds exactly the last 60 seconds' adds
    assert ts.sum("1min", now=1299.0) == 60.0
    assert ts.rate("1min", now=1299.0) == 1.0
    # all-time never windows
    assert ts.all_time() == (300.0, 300)
    # an idle gap wider than the 1min ring drains it; wider levels
    # still hold what their windows cover
    assert ts.sum("1min", now=1299.0 + 120) == 0.0
    assert ts.sum("10min", now=1299.0 + 120) > 0.0
    with pytest.raises(KeyError):
        ts.rate("2min")


def test_stat_family_cardinality_bounded():
    """A client looping over random stream names must not grow the
    series map without bound: past TS_MAX_LABELS keys per family, new
    keys fold into one overflow series (the histogram discipline)."""
    from hstream_tpu.stats import TS_MAX_LABELS, TS_OVERFLOW_LABEL

    stats = StatsHolder()
    for i in range(TS_MAX_LABELS + 40):
        stats.stat_add("append_in_bytes", f"junk-{i}", 10.0)
    keys = stats.stat_keys("append_in_bytes")
    assert len(keys) == TS_MAX_LABELS + 1
    assert TS_OVERFLOW_LABEL in keys
    lad = stats.stat_ladder("append_in_bytes", TS_OVERFLOW_LABEL)
    assert lad["total"] == 400.0
    # existing keys keep accumulating normally past the cap
    stats.stat_add("append_in_bytes", "junk-0", 5.0)
    assert stats.stat_ladder("append_in_bytes", "junk-0")["total"] == 15.0
    # other families are unaffected by this family's fold
    stats.stat_add("record_bytes", "fresh", 1.0)
    assert stats.stat_keys("record_bytes") == ["fresh"]


def test_unregistered_gauge_and_histogram_raise():
    stats = StatsHolder()
    with pytest.raises(KeyError):
        stats.gauge_set("bogus_gauge", "", 1.0)
    with pytest.raises(KeyError):
        stats.observe("bogus_hist", "", 1.0)


def test_histogram_label_cardinality_bounded():
    """A client looping over garbage stream names (failed RPCs still
    observe latency) must not grow /metrics without bound: past the
    per-metric cap, new labels fold into one overflow series."""
    from hstream_tpu.stats import HIST_MAX_LABELS, HIST_OVERFLOW_LABEL

    stats = StatsHolder()
    for i in range(HIST_MAX_LABELS + 50):
        stats.observe("append_latency_ms", f"junk-{i}", 1.0)
    hists = stats.histograms_snapshot()
    assert len(hists) == HIST_MAX_LABELS + 1
    overflow = hists[("append_latency_ms", HIST_OVERFLOW_LABEL)]
    assert overflow.count == 50
    # existing labels keep observing normally past the cap
    stats.observe("append_latency_ms", "junk-0", 1.0)
    assert hists[("append_latency_ms", "junk-0")].count == 2


def test_gauge_fn_samples_and_drops_dead():
    stats = StatsHolder()
    items = [1, 2, 3]
    stats.gauge_fn("event_journal_size", "", lambda: len(items))
    assert stats.gauges_snapshot()[("event_journal_size", "")] == 3.0
    items.append(4)
    assert stats.gauges_snapshot()[("event_journal_size", "")] == 4.0

    def dead():
        raise RuntimeError("subsystem gone")

    stats.gauge_fn("running_queries", "", dead)
    snap = stats.gauges_snapshot()  # drops the raising sampler
    assert ("running_queries", "") not in snap
    assert ("running_queries", "") not in stats.gauges_snapshot()
    assert stats.gauge_labels("running_queries") == []


# ---- exposition correctness ------------------------------------------------


def _golden_holder() -> StatsHolder:
    """Deterministic holder state for the golden-file exposition."""
    stats = StatsHolder()
    stats.stream_stat_add("append_total", "s1", 3)
    stats.stream_stat_add("append_payload_bytes", "s1", 4096)
    stats.stream_stat_add("record_total", "s2", 7)
    # freshness/attribution counters (ISSUE 13): late drops are
    # query-labeled, factory recompiles family-labeled — both must
    # render (and survive liveness filtering, asserted elsewhere)
    stats.stream_stat_add("late_drops", "q1", 2)
    stats.stream_stat_add("factory_recompiles", "step", 1)
    stats.stream_stat_add("device_h2d_bytes", "s1", 1024)
    stats.stream_stat_add("device_d2h_bytes", "s1", 512)
    # rate ladders (ISSUE 15): adds stamped far in the past render a
    # deterministic 0.0 in every trailing window — the golden checks
    # the family/scope label plumbing and the stream_rate ladder
    # layout, not wall-clock-dependent values
    stats.stat_add("append_in_bytes", "s1", 4096.0, now=BASE / 1000)
    stats.stat_add("append_in_records", "s1", 3.0, now=BASE / 1000)
    stats.stat_add("delivered_records", "sub1", 7.0, now=BASE / 1000)
    stats.stat_add("emit_rows", "q1", 5.0, now=BASE / 1000)
    stats.gauge_set("overload_level", "", 1)
    stats.gauge_set("running_queries", "", 2)
    stats.gauge_set("pipeline_occupancy", "q1", 0.5)
    # freshness plane gauges (query-labeled)
    stats.gauge_set("query_watermark_ms", "q1", 1_700_000_000_000)
    stats.gauge_set("query_watermark_lag_ms", "q1", 250.0)
    stats.gauge_set("query_health_level", "q1", 1)
    # device cost plane gauges (ISSUE 18): per-query HBM total, one
    # per-plane series (composite "qid/plane" label splits into
    # {query, plane} at render), process total + backend cross-check
    stats.gauge_set("device_hbm_bytes", "q1", 4096)
    stats.gauge_set("device_arena_bytes", "q1/count", 2048)
    stats.gauge_set("device_arena_bytes", "q1/agg0_sum", 2048)
    stats.gauge_set("device_hbm_total_bytes", "", 4096)
    stats.gauge_set("device_hbm_backend_bytes", "", 8192)
    for v in (0.4, 3.0, 40.0):
        stats.observe("append_latency_ms", "s1", v)
    # freshness histograms: per-stage lag + visible latency + emit
    for stage, v in (("ingest", 4.0), ("engine", 30.0),
                     ("delivery", 120.0)):
        stats.observe("freshness_lag_ms", stage, v)
    stats.observe("append_visible_latency_ms", "q1", 45.0)
    stats.observe("emit_latency_ms", "q1", 12.0)
    stats.observe("kernel_dispatch_ms", "step", 1.5)
    # device-time sampler histogram (ISSUE 18) next to the host wall
    stats.observe("kernel_device_ms", "step", 0.9)
    # lock-order witness ledger (ISSUE 14): wait/hold + contention
    stats.stream_stat_add("lock_contention", "tasks.state", 3)
    stats.observe("lock_wait_ms", "tasks.state", 0.8)
    stats.observe("lock_hold_ms", "tasks.state", 2.0)
    # read plane (ISSUE 20): view-labeled extract counter, the
    # read_out_records rate ladder, and the cache gauges
    stats.stream_stat_add("read_extracts", "v1", 2)
    stats.stat_add("read_out_records", "v1", 9.0, now=BASE / 1000)
    stats.gauge_set("read_cache_hit_ratio", "", 0.75)
    stats.gauge_set("read_cache_bytes", "", 16384)
    return stats


def test_metrics_golden_file():
    """The exposition of a fixed holder state matches the checked-in
    golden byte-for-byte (naming, ordering, HELP/TYPE headers, label
    quoting, bucket layout). Regenerate deliberately with:
    python -c "from tests.test_observability import _write_golden; \
_write_golden()" (from the repo root, tests on sys.path)."""
    got = render_holder(_golden_holder())
    with open(GOLDEN, encoding="utf-8") as f:
        want = f.read()
    assert got == want


def _write_golden() -> None:
    with open(GOLDEN, "w", encoding="utf-8") as f:
        f.write(render_holder(_golden_holder()))


def test_label_escaping():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    stats = StatsHolder()
    evil = 'str"eam\\with\nnasties'
    stats.stream_stat_add("append_total", evil)
    text = render_holder(stats)
    line = [ln for ln in text.splitlines()
            if ln.startswith("hstream_append_total{")][0]
    assert line == ('hstream_append_total{stream='
                    '"str\\"eam\\\\with\\nnasties"} 1')


def test_histogram_bucket_monotonicity_and_naming():
    h = Histogram((1.0, 5.0, 25.0))
    for v in (0.2, 0.7, 3.0, 100.0, 4.0, 30.0):
        h.observe(v)
    cum, total_sum, count = h.snapshot()
    assert count == 6 and abs(total_sum - 137.9) < 1e-9
    assert cum == sorted(cum), "cumulative buckets must be monotone"
    assert cum[-1] == count, "+Inf bucket must equal _count"
    stats = StatsHolder()
    stats.observe("fetch_latency_ms", "sub1", 2.0)
    text = render_holder(stats)
    assert "hstream_fetch_latency_ms_bucket{subscription=\"sub1\"," in text
    assert 'le="+Inf"' in text
    assert "hstream_fetch_latency_ms_sum{subscription=\"sub1\"}" in text
    assert "hstream_fetch_latency_ms_count{subscription=\"sub1\"}" in text
    # counters carry the _total suffix exactly once
    stats.stream_stat_add("append_total", "s")
    stats.stream_stat_add("shed_total", "s")
    text = render_holder(stats)
    assert "hstream_append_total{" in text
    assert "hstream_append_total_total" not in text
    assert "hstream_shed_total{" in text


def test_histogram_percentiles():
    h = Histogram((1.0, 10.0, 100.0))
    for _ in range(99):
        h.observe(0.5)
    h.observe(50.0)
    assert h.percentile(50) <= 1.0
    assert 10.0 <= h.percentile(100) <= 100.0
    assert Histogram((1.0,)).percentile(50) is None


def test_live_metrics_endpoint_covers_registries(stack):
    """GET /metrics (gateway) renders valid exposition lines covering
    counters, rates, >= 6 gauges and >= 3 histograms after the RPC
    surface has been exercised."""
    addr, base, stub, ctx = stack
    from hstream_tpu.common import records as rec

    stub.CreateStream(pb.Stream(stream_name="mx"))
    req = pb.AppendRequest(stream_name="mx")
    for i in range(3):
        req.records.append(rec.build_record(
            {"k": "a", "v": i}, publish_time_ms=BASE + i))
    stub.Append(req)
    stub.ExecuteQuery(pb.CommandQuery(stmt_text="SHOW STREAMS;"))
    stub.CreateSubscription(pb.Subscription(
        subscription_id="mxsub", stream_name="mx"))
    stub.Fetch(pb.FetchRequest(subscription_id="mxsub",
                               timeout_ms=200, max_size=10))
    # a running query task exercises stage histograms + pipeline gauges
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM mx GROUP BY k, "
                   "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"))
    from helpers import wait_attached

    wait_attached(ctx, q.id)
    req2 = pb.AppendRequest(stream_name="mx")
    for i in range(4):
        req2.records.append(rec.build_record(
            {"k": "b", "v": i}, publish_time_ms=BASE + 100 + i))
    stub.Append(req2)
    deadline = time.time() + 20
    while time.time() < deadline:
        task = ctx.running_queries.get(q.id)
        if task is not None and task.executor is not None:
            break
        time.sleep(0.05)

    code, body, headers = _http("GET", base, "/metrics")
    assert code == 200
    assert headers["Content-Type"].startswith("text/plain")
    text = body.decode()
    # structural validity: every non-comment line is `name{labels} value`
    line_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+$|'
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [0-9.]*inf$', re.I)
    for ln in text.splitlines():
        if ln.startswith("#") or not ln:
            continue
        assert line_re.match(ln), f"malformed exposition line: {ln}"
    assert "hstream_append_total{" in text
    assert "hstream_append_in_bytes_rate{" in text
    gauges_seen = {g for g in GAUGES if f"hstream_{g}" in text}
    assert len(gauges_seen) >= 6, gauges_seen
    hists_seen = {h for h, _b, _l in HISTOGRAMS
                  if f"hstream_{h}_bucket" in text}
    assert len(hists_seen) >= 3, hists_seen
    # bucket monotonicity on the live append histogram
    buckets = [float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
               if ln.startswith("hstream_append_latency_ms_bucket{"
                                "stream=\"mx\"")]
    assert buckets and buckets == sorted(buckets)
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))
    stub.DeleteSubscription(pb.DeleteSubscriptionRequest(
        subscription_id="mxsub"))


def test_standalone_exporter(stack):
    """--metrics-port serves /metrics + /events straight off the server
    process (no gateway hop)."""
    _, _, _, ctx = stack
    port = ctx.metrics_httpd.server_port
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as r:
        assert r.status == 200
        assert "hstream_running_queries" in r.read().decode()
    ctx.events.append("query_restarted", "exporter probe", query="p1")
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/events?kind=query_restarted"
            f"&limit=5") as r:
        events = json.loads(r.read())
    assert any(e["message"] == "exporter probe" for e in events)


# ---- event journal ---------------------------------------------------------


def test_journal_ring_bounds_under_concurrent_writers():
    j = EventJournal(capacity=100)
    n_threads, per_thread = 8, 500

    def writer(i):
        for k in range(per_thread):
            j.append("shed_level", f"w{i}-{k}", level="defer")

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(j) == 100
    assert j.last_seq == n_threads * per_thread
    entries = j.query(limit=1000)
    seqs = [e["seq"] for e in entries]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert seqs[-1] == j.last_seq


def test_journal_rejects_unregistered_kind():
    j = EventJournal()
    with pytest.raises(KeyError):
        j.append("made_up_kind", "nope")


def test_journal_query_filters():
    j = EventJournal(capacity=10)
    j.append("shed_level", "a", level="defer")
    j.append("query_died", "b", query="q1")
    j.append("shed_level", "c", level="admit")
    assert [e["message"] for e in j.query(kind="shed_level")] == ["a", "c"]
    assert [e["message"] for e in j.query(since=2)] == ["c"]
    assert len(j.query(limit=1)) == 1


def test_events_admin_verb_and_gateway_route(stack):
    addr, base, stub, ctx = stack
    from hstream_tpu.common import records as rec

    # a real ladder transition journals itself
    ctx.flow.overload.note("step_latency_ms", 1e6, source="evt-test")
    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command="events",
        args=rec.dict_to_struct({"kind": "shed_level", "limit": 10})))
    events = json.loads(resp.result)["events"]
    assert events and events[-1]["kind"] == "shed_level"
    code, body, _ = _http("GET", base,
                          "/events?kind=shed_level&limit=5")
    assert code == 200
    assert any(e["kind"] == "shed_level" for e in json.loads(body))
    # admin CLI renders the same verb
    from hstream_tpu.admin import main as admin_main

    host, port = addr.split(":")
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = admin_main(["--host", host, "--port", port,
                         "events", "--kind", "shed_level"])
    assert rc == 0 and "shed_level" in buf.getvalue()
    # let the detector's source expire instead of pinning REJECT for
    # the rest of the module (10s staleness; force recompute now)
    ctx.flow.overload._sigs["step_latency_ms"].sources.clear()
    ctx.flow.overload.effective_level()


# ---- request correlation ---------------------------------------------------


class _Capture(logging.Handler):
    """Captures (message, active request id) pairs: emit runs in the
    logging thread, where the handler's contextvar is bound."""

    def __init__(self):
        super().__init__()
        self.records: list[tuple[str, str]] = []

    def emit(self, record):
        self.records.append((record.getMessage(), current_request_id()))


def test_correlation_id_client_gateway_handler(stack):
    """One id follows a request end to end: the HTTP caller's
    X-Request-Id reaches the handler's log records (via gRPC metadata
    and the logger contextvar) and echoes back on the response."""
    addr, base, stub, ctx = stack
    cap = _Capture()
    root = logging.getLogger("hstream_tpu")
    root.addHandler(cap)
    old_slow = ctx.slow_request_ms
    ctx.slow_request_ms = 0.0  # every RPC logs a slow-request line
    try:
        _http("POST", base, "/streams", {"name": "corr"})
        code, _, headers = _http(
            "POST", base, "/streams/corr/append",
            {"records": [{"a": 1}]},
            headers={"X-Request-Id": "corr-test-1"})
        assert code == 200
        assert headers["X-Request-Id"] == "corr-test-1"
        hits = [rid for msg, rid in cap.records
                if "slow request" in msg and "Append" in msg]
        assert "corr-test-1" in hits
        # gateway mints an id when the caller sends none
        cap.records.clear()
        code, _, headers = _http("POST", base, "/streams/corr/append",
                                 {"records": [{"a": 2}]})
        minted = headers["X-Request-Id"]
        assert minted.startswith("gw-")
        assert any(rid == minted for msg, rid in cap.records
                   if "slow request" in msg and "Append" in msg)
        # the SQL client stamps its own ids on direct gRPC calls
        cap.records.clear()
        client = Client(addr, out=io.StringIO())
        try:
            client.execute("SHOW STREAMS;")
            assert client.last_request_id is not None
            assert any(rid == client.last_request_id
                       for msg, rid in cap.records
                       if "slow request" in msg
                       and "ExecuteQuery" in msg)
        finally:
            client.close()
    finally:
        ctx.slow_request_ms = old_slow
        root.removeHandler(cap)


def test_slow_request_threshold_gates_logging(stack):
    _, base, _, ctx = stack
    cap = _Capture()
    root = logging.getLogger("hstream_tpu")
    root.addHandler(cap)
    old_slow = ctx.slow_request_ms
    ctx.slow_request_ms = 60_000.0  # nothing is that slow
    try:
        _http("GET", base, "/streams")
        assert not any("slow request" in msg
                       for msg, _rid in cap.records)
    finally:
        ctx.slow_request_ms = old_slow
        root.removeHandler(cap)


def test_query_tracer_carries_request_id(stack):
    addr, base, stub, ctx = stack
    from hstream_tpu.common import records as rec
    from helpers import wait_attached

    stub.CreateStream(pb.Stream(stream_name="tracesrc"))
    q = stub.CreateQuery(
        pb.CreateQueryRequest(
            query_text="SELECT k, COUNT(*) AS c FROM tracesrc GROUP BY "
                       "k, TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"),
        metadata=(("x-request-id", "trace-rid-9"),))
    task = wait_attached(ctx, q.id)
    assert task.tracer.request_id == "trace-rid-9"
    req = pb.AppendRequest(stream_name="tracesrc")
    req.records.append(rec.build_record({"k": "z"},
                                        publish_time_ms=BASE))
    stub.Append(req)
    deadline = time.time() + 20
    while time.time() < deadline:
        summary = task.tracer.summary()
        if summary.get("request"):
            break
        time.sleep(0.05)
    assert task.tracer.summary()["request"]["id"] == "trace-rid-9"
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))


# ---- freshness / trace spans / health plane (ISSUE 13) ---------------------


def _append_rows(stub, stream, rows_ts, key="k"):
    from hstream_tpu.common import records as rec

    req = pb.AppendRequest(stream_name=stream)
    for kval, ts in rows_ts:
        req.records.append(rec.build_record({key: kval},
                                            publish_time_ms=ts))
    stub.Append(req)


def _wait_watermark(ctx, qid, target, timeout=20):
    from hstream_tpu.server.health import _executor_watermark

    deadline = time.time() + timeout
    while time.time() < deadline:
        task = ctx.running_queries.get(qid)
        if task is not None:
            wm = _executor_watermark(task)
            if wm is not None and wm >= target:
                return task
        time.sleep(0.05)
    raise TimeoutError(f"query {qid} never reached watermark {target}")


def test_trace_export_roundtrip(stack):
    """Client -> gateway -> handler -> task spans share ONE trace id
    (the request id), and the export is valid Chrome trace-event
    JSON."""
    addr, base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="trsrc"))
    req = urllib.request.Request(
        base + "/queries",
        data=json.dumps({"sql": "SELECT k, COUNT(*) AS c FROM trsrc "
                                "GROUP BY k, TUMBLING (INTERVAL 1 "
                                "SECOND) GRACE BY INTERVAL 0 SECOND "
                                "EMIT CHANGES;",
                         "id": "qtr1"}).encode(),
        method="POST",
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "trace-rt-7"})
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["id"] == "qtr1"
    now = int(time.time() * 1000)
    _append_rows(stub, "trsrc", [(f"k{i % 3}", now + i)
                                 for i in range(32)])
    _wait_watermark(ctx, "qtr1", now + 31)
    code, body, _ = _http("GET", base, "/queries/qtr1/trace")
    assert code == 200
    trace = json.loads(body)
    events = trace["traceEvents"]
    assert events, "no spans exported"
    assert {e["args"]["trace_id"] for e in events} == {"trace-rt-7"}
    names = {e["name"] for e in events}
    assert "rpc" in names, names          # the CreateQuery handler span
    assert "step" in names, names         # the task's device-step span
    for e in events:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], (int, float))
        assert e["dur"] >= 1
        assert e["args"]["span_id"]
    # the handler span parents the task's stage spans (one chain)
    rpc = next(e for e in events if e["name"] == "rpc")
    stage = next(e for e in events if e["name"] == "step")
    assert stage["args"]["parent_id"] == rpc["args"]["span_id"]
    # the gateway hop named itself as the handler span's parent
    assert rpc["args"]["parent_id"] == "gw-trace-rt-7"
    # admin trace --spans prints the same export as JSON
    from hstream_tpu.admin import main as admin_main
    import contextlib

    host, port = addr.split(":")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = admin_main(["--host", host, "--port", port,
                         "trace", "qtr1", "--spans"])
    assert rc == 0
    spans = json.loads(buf.getvalue().splitlines()[0])
    assert spans["traceEvents"]
    stub.DeleteQuery(pb.DeleteQueryRequest(id="qtr1"))


def test_unsampled_requests_record_no_spans(stack):
    """A request with no request id has no trace id, so nothing lands
    in the rings even with tracing armed (sampling is per-trace and
    deterministic)."""
    addr, base, stub, ctx = stack
    before = ctx.tracing.spans("_rpc")
    stub.ListStreams(pb.ListStreamsRequest())  # bare: no metadata
    assert ctx.tracing.spans("_rpc") == before


def test_freshness_plane_on_live_server(stack):
    """Watermark gauges, per-stage lag histograms, append->visible and
    emit latency, kernel-family dispatch histograms, and the late-drop
    counter all surface on /metrics from a live query."""
    addr, base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="fpsrc"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM fpsrc GROUP BY k, "
                   "TUMBLING (INTERVAL 1 SECOND) GRACE BY INTERVAL 0 "
                   "SECOND EMIT CHANGES;", id="qfp1"))
    now = int(time.time() * 1000)
    _append_rows(stub, "fpsrc", [(f"k{i % 4}", now + i)
                                 for i in range(64)])
    _wait_watermark(ctx, q.id, now + 63)
    # one LATE record: past close at the current watermark
    _append_rows(stub, "fpsrc", [("late", now - 3_600_000),
                                 ("fresh", now + 100)])
    deadline = time.time() + 20
    while time.time() < deadline:
        if ctx.stats.stream_stat_get("late_drops", q.id) >= 1:
            break
        time.sleep(0.05)
    assert ctx.stats.stream_stat_get("late_drops", q.id) >= 1
    code, body, _ = _http("GET", base, "/metrics")
    text = body.decode()
    assert f'hstream_query_watermark_ms{{query="{q.id}"}}' in text
    assert f'hstream_query_watermark_lag_ms{{query="{q.id}"}}' in text
    assert f'hstream_query_health_level{{query="{q.id}"}}' in text
    assert 'hstream_freshness_lag_ms_bucket{stage="ingest"' in text
    assert 'hstream_freshness_lag_ms_bucket{stage="engine"' in text
    assert ('hstream_append_visible_latency_ms_bucket{consumer='
            f'"{q.id}"') in text
    assert f'hstream_emit_latency_ms_bucket{{query="{q.id}"' in text
    assert 'hstream_kernel_dispatch_ms_bucket{family="step"' in text
    assert re.search(
        rf'hstream_late_drops_total\{{stream="{q.id}"\}} [1-9]', text)
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))


def test_delivery_stage_lag_from_subscription(stack):
    addr, base, stub, ctx = stack
    from hstream_tpu.common import records as rec

    stub.CreateStream(pb.Stream(stream_name="dlsrc"))
    req = pb.AppendRequest(stream_name="dlsrc")
    req.records.append(rec.build_record({"a": 1}))
    stub.Append(req)
    stub.CreateSubscription(pb.Subscription(
        subscription_id="dlsub", stream_name="dlsrc"))
    got = stub.Fetch(pb.FetchRequest(subscription_id="dlsub",
                                     timeout_ms=500, max_size=10))
    assert got.received_records
    code, body, _ = _http("GET", base, "/metrics")
    text = body.decode()
    assert 'hstream_freshness_lag_ms_bucket{stage="delivery"' in text
    assert ('hstream_append_visible_latency_ms_bucket{consumer='
            '"dlsub"') in text
    stub.DeleteSubscription(pb.DeleteSubscriptionRequest(
        subscription_id="dlsub"))


def test_health_endpoint_ok_and_unknown(stack):
    addr, base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="hlsrc"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM hlsrc GROUP BY k, "
                   "TUMBLING (INTERVAL 1 SECOND) EMIT CHANGES;",
        id="qhl1"))
    now = int(time.time() * 1000)
    _append_rows(stub, "hlsrc", [("a", now)])
    _wait_watermark(ctx, q.id, now)
    code, body, _ = _http("GET", base, f"/queries/{q.id}/health")
    assert code == 200
    h = json.loads(body)
    assert h["verdict"] == "OK" and h["level"] == 0, h
    assert h["reasons"] == []
    assert h["watermark_ms"] == now
    assert h["thresholds"]["stalled_after_ms"] == 30000.0
    # unknown query -> 404 through the typed-error mapping
    try:
        urllib.request.urlopen(base + "/queries/nope/health")
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))


def test_health_stalled_crash_loop_journals_event(stack):
    """A crash-looped query reads STALLED (reason crash_loop) and the
    transition journals exactly one query_stalled event; operator
    RestartQuery resets the breaker and health recovers."""
    addr, base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="clsrc"))
    q = stub.CreateQuery(pb.CreateQueryRequest(
        query_text="SELECT k, COUNT(*) AS c FROM clsrc GROUP BY k, "
                   "TUMBLING (INTERVAL 1 SECOND) EMIT CHANGES;",
        id="qcl1"))
    from helpers import wait_attached

    task = wait_attached(ctx, q.id)
    # kill the task for real (crash mode: no final snapshot, status
    # stays RUNNING), then feed the supervisor a crash loop
    task.stop(crash=True)
    deadline = time.time() + 10
    while q.id in ctx.running_queries and time.time() < deadline:
        time.sleep(0.02)
    assert q.id not in ctx.running_queries
    info = ctx.persistence.get_query(q.id)
    sup = ctx.supervisor
    for _ in range(sup.BREAKER_K):
        sup.note_death(info, RuntimeError("boom"))
    assert q.id in sup.status()["breaker_open"]
    seq0 = ctx.events.last_seq
    code, body, _ = _http("GET", base, f"/queries/{q.id}/health")
    h = json.loads(body)
    assert h["verdict"] == "STALLED" and "crash_loop" in h["reasons"]
    events = ctx.events.query(kind="query_stalled", since=seq0 - 50)
    assert any(e.get("query") == q.id for e in events)
    # re-evaluation does NOT re-journal (transition memory)
    n_before = len(ctx.events.query(kind="query_stalled", limit=1000))
    _http("GET", base, f"/queries/{q.id}/health")
    assert len(ctx.events.query(kind="query_stalled",
                                limit=1000)) == n_before
    # operator restart closes the breaker; health recovers
    stub.RestartQuery(pb.RestartQueryRequest(id=q.id))
    code, body, _ = _http("GET", base, f"/queries/{q.id}/health")
    h = json.loads(body)
    assert h["verdict"] == "OK", h
    stub.DeleteQuery(pb.DeleteQueryRequest(id=q.id))


def test_health_unowned_only_when_this_node_owns(stack):
    """A RUNNING query with no local task is STALLED(unowned) only
    when the scheduler record names THIS node (or nobody) — a query
    owned by a live peer is that peer's to judge, never false
    distress from a bystander's scrape."""
    import json as _json

    from hstream_tpu.server import scheduler
    from hstream_tpu.server.persistence import (
        QueryInfo,
        TaskStatus,
        now_ms,
    )

    addr, base, stub, ctx = stack
    info = QueryInfo(query_id="qpeer1", sql="SELECT 1;",
                     created_time_ms=now_ms(),
                     status=TaskStatus.RUNNING, sink="qpeer1")
    ctx.persistence.insert_query(info)
    try:
        # owned by a live PEER (higher epoch): not ours to judge
        ctx.config.put(
            scheduler._key("qpeer1"),
            _json.dumps({"node": "server-9@peer:6570",
                         "epoch": ctx.boot_epoch + 1}).encode())
        code, body, _ = _http("GET", base, "/queries/qpeer1/health")
        h = json.loads(body)
        assert h["verdict"] == "OK", h
        assert h["owner"] == "server-9@peer:6570"
        # re-owned by THIS node, still no task: genuinely unowned
        cur = ctx.config.get(scheduler._key("qpeer1"))
        ctx.config.put(
            scheduler._key("qpeer1"),
            _json.dumps({"node": scheduler.node_name(ctx),
                         "epoch": ctx.boot_epoch}).encode(),
            base_version=cur[0])
        code, body, _ = _http("GET", base, "/queries/qpeer1/health")
        h = json.loads(body)
        assert h["verdict"] == "STALLED" and "unowned" in h["reasons"]
    finally:
        ctx.persistence.remove_query("qpeer1")
        cur = ctx.config.get(scheduler._key("qpeer1"))
        if cur is not None:
            ctx.config.delete(scheduler._key("qpeer1"),
                              base_version=cur[0])


def test_host_device_session_freshness_parity():
    """The freshness plane reads the same host-mirror values whichever
    engine ran the batch: device and host session executors agree on
    the watermark AND the late-drop count for an identical feed."""
    import numpy as np

    from hstream_tpu.engine import ColumnType, Schema
    from hstream_tpu.engine.expr import Col
    from hstream_tpu.engine.plan import (
        AggKind,
        AggregateNode,
        AggSpec,
        SourceNode,
    )
    from hstream_tpu.engine.session import SessionExecutor
    from hstream_tpu.engine.window import SessionWindow

    def mk():
        schema = Schema.of(u=ColumnType.STRING, v=ColumnType.FLOAT)
        node = AggregateNode(
            child=SourceNode("s", schema), group_keys=[Col("u")],
            window=SessionWindow(1_000, grace_ms=0),
            aggs=[AggSpec(AggKind.COUNT_ALL, "c")])
        return SessionExecutor(node, schema, emit_changes=False)

    dev, host = mk(), mk()
    host.use_device_sessions = False
    base = 1_700_000_000_000
    users = np.array(["a", "b", "c", "d"])
    feeds = [
        (base + np.arange(8, dtype=np.int64) * 100,
         {"u": users[np.arange(8) % 4], "v": np.ones(8, np.float32)}),
        # far ahead: closes the first sessions and advances the wm
        (base + 60_000 + np.arange(8, dtype=np.int64) * 100,
         {"u": users[np.arange(8) % 4], "v": np.ones(8, np.float32)}),
        # LATE: all 8 records are past gap+grace at the watermark
        (base + 10_000 + np.arange(8, dtype=np.int64),
         {"u": users[np.arange(8) % 4], "v": np.ones(8, np.float32)}),
    ]
    out_dev, out_host = [], []
    for ts, cols in feeds:
        out_dev.extend(dev.process_columnar(ts, dict(cols)))
        out_host.extend(host.process_columnar(ts, dict(cols)))
    out_dev.extend(dev.drain_closed())
    out_host.extend(host.drain_closed())
    assert dev._dev is not None, "device path did not activate"
    assert dev.watermark == host.watermark
    assert dev.late_drops == host.late_drops == 8
    assert len(out_dev) == len(out_host)


def test_query_label_counters_survive_stream_filter():
    """late_drops / kernel_recompiles series are query-labeled: the
    live-STREAM filter must not drop them (bounded by query existence
    instead), and factory_recompiles is never liveness-filtered."""
    stats = StatsHolder()
    stats.stream_stat_add("late_drops", "q9", 4)
    stats.stream_stat_add("kernel_recompiles", "q9", 2)
    stats.stream_stat_add("factory_recompiles", "probe", 1)
    text = render_holder(stats, live_streams=set(), live_queries={"q9"})
    assert 'hstream_late_drops_total{stream="q9"} 4' in text
    assert 'hstream_kernel_recompiles_total{stream="q9"} 2' in text
    assert 'hstream_factory_recompiles_total{stream="probe"} 1' in text
    # deleted query: its series leave the exposition
    text = render_holder(stats, live_streams=set(), live_queries=set())
    assert "q9" not in text
    assert 'hstream_factory_recompiles_total{stream="probe"} 1' in text


def test_lock_label_counters_survive_stream_filter():
    """lock_contention is labeled by a traced-lock ROLE name — never a
    stream, so the liveness filter must not drop it; the wait/hold
    histograms carry the `lock` label key (ISSUE 14)."""
    stats = StatsHolder()
    stats.stream_stat_add("lock_contention", "tasks.state", 5)
    stats.observe("lock_wait_ms", "tasks.state", 1.2)
    stats.observe("lock_hold_ms", "scheduler.supervisor", 0.3)
    text = render_holder(stats, live_streams=set(), live_queries=set())
    assert 'hstream_lock_contention_total{stream="tasks.state"} 5' \
        in text
    assert 'hstream_lock_wait_ms_count{lock="tasks.state"} 1' in text
    assert 'hstream_lock_hold_ms_count{lock="scheduler.supervisor"} 1' \
        in text


# ---- /overview wiring (satellite) ------------------------------------------


def test_overview_includes_flow_and_pipeline(stack):
    _, base, stub, ctx = stack
    code, body, _ = _http("GET", base, "/overview")
    assert code == 200
    ov = json.loads(body)
    assert ov["flow"]["level"] in ("admit", "defer", "reject")
    assert "shed" in ov["flow"] and "signals" in ov["flow"]
    assert "pipeline_stages" in ov


# ---- registry lint ---------------------------------------------------------


def test_json_append_hits_native_decoder(stack):
    """ISSUE 5 satellite: a multi-record JSON append must be decoded by
    the libjsondec batch decoder, not the per-record Python fallback —
    and the native/fallback split is visible in /metrics."""
    from hstream_tpu.common import jsondec
    from hstream_tpu.common import records as rec

    if jsondec.load() is None:
        pytest.skip("native jsondec unavailable (no toolchain)")
    addr, http_base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="njd"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE STREAM njd_out AS SELECT device, COUNT(*) "
                  "AS c FROM njd GROUP BY device, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
    from helpers import wait_any_attached

    wait_any_attached(ctx)
    req = pb.AppendRequest(stream_name="njd")
    for i in range(64):
        req.records.append(rec.build_record(
            {"device": f"d{i % 4}", "temp": 1.5},
            publish_time_ms=BASE + i))
    stub.Append(req)
    deadline = time.time() + 20
    while time.time() < deadline:
        if ctx.stats.stream_stat_get("json_decode_native", "njd") >= 64:
            break
        time.sleep(0.05)
    native = ctx.stats.stream_stat_get("json_decode_native", "njd")
    assert native >= 64, f"native decode counter stuck at {native}"
    assert ctx.stats.stream_stat_get("json_decode_fallback", "njd") == 0
    body = render_metrics(ctx)
    assert re.search(
        r'hstream_json_decode_native_total\{stream="njd"\} \d+', body)


def test_metrics_lint_passes():
    """The registry check now lives in the analysis suite (ISSUE 4)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--only", "registry"],
        capture_output=True, text=True, cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr


def test_metrics_lint_shim_forwards():
    """The deprecated tools/metrics_lint.py entry point still works
    (forwards to the registry pass with a deprecation warning)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "metrics_lint.py")],
        capture_output=True, text=True, cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DEPRECATED" in r.stderr
