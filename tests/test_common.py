from hstream_tpu.common import (
    build_record,
    flatten_json,
    gen_unique,
    parse_record,
    record_to_dict,
)
from hstream_tpu.proto import api_pb2 as pb


def test_record_json_roundtrip():
    rec = build_record({"temp": 25, "name": "dev1", "ok": True, "x": 1.5},
                       key="k1", attributes={"a": "b"})
    data = rec.SerializeToString()
    back = parse_record(data)
    assert back.header.flag == pb.RECORD_FLAG_JSON
    assert back.header.key == "k1"
    assert back.header.attributes["a"] == "b"
    assert back.header.publish_time_ms > 0
    d = record_to_dict(back)
    assert d == {"temp": 25, "name": "dev1", "ok": True, "x": 1.5}
    assert isinstance(d["temp"], int)  # integral floats decode to int


def test_record_raw():
    rec = build_record(b"\x00\x01binary")
    assert rec.header.flag == pb.RECORD_FLAG_RAW
    assert record_to_dict(rec) is None
    assert rec.payload == b"\x00\x01binary"


def test_flatten_json():
    assert flatten_json({"a": {"b": {"c": 1}, "d": 2}, "e": [1, 2]}) == {
        "a.b.c": 1, "a.d": 2, "e": [1, 2]}


def test_gen_unique():
    ids = [gen_unique() for _ in range(1000)]
    assert len(set(ids)) == 1000
    assert all(i > 0 for i in ids)
