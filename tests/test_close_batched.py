"""Fused window-close tests (ISSUE 5).

The close path's contract: one lattice-kernel dispatch and one
device->host fetch per close cycle, however many windows are due, with
results held columnar (common.columnar.ColumnarEmit) until a row-shaped
consumer materializes them. Equivalence is asserted against the legacy
per-slot kernels (lattice.build_extract_slot / build_reset_slot, kept
compiled exactly for this reference role).
"""

import numpy as np
import pytest

from hstream_tpu.common.columnar import (
    ColumnarEmit,
    decode_columnar,
    extend_rows,
    rows_to_payload,
    to_rows,
)
from hstream_tpu.engine import (
    AggKind,
    AggSpec,
    AggregateNode,
    ColumnType,
    HoppingWindow,
    QueryExecutor,
    Schema,
    SourceNode,
    TumblingWindow,
)
from hstream_tpu.engine import lattice
from hstream_tpu.engine.expr import BinOp, Col, Lit, UnOp

SCHEMA = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
BASE = 1_700_000_000_000

COUNT = AggSpec(AggKind.COUNT_ALL, "cnt")
SUM_T = AggSpec(AggKind.SUM, "total", input=Col("temp"))
MIN_T = AggSpec(AggKind.MIN, "mn", input=Col("temp"))
AVG_T = AggSpec(AggKind.AVG, "avg", input=Col("temp"))
UNIQ_T = AggSpec(AggKind.APPROX_COUNT_DISTINCT, "u", input=Col("temp"))


def make_exec(aggs, window, *, emit_changes=False, having=None,
              post=None, initial_keys=8):
    node = AggregateNode(
        child=SourceNode("s", SCHEMA), group_keys=[Col("device")],
        window=window, aggs=list(aggs), having=having,
        post_projections=post or [])
    return QueryExecutor(node, SCHEMA, emit_changes=emit_changes,
                         initial_keys=initial_keys, batch_capacity=256)


def rows_of(*pairs):
    rows = [{"device": d, "temp": t} for d, t, _ in pairs]
    ts = [BASE + off for _, _, off in pairs]
    return rows, ts


def gen(n, n_keys=6, span_ms=35_000, seed=0):
    rng = np.random.default_rng(seed)
    rows = [{"device": f"d{int(k)}", "temp": float(t)}
            for k, t in zip(rng.integers(0, n_keys, n),
                            rng.normal(10, 4, n).astype(np.float32))]
    ts = [BASE + int(t) for t in np.sort(rng.integers(0, span_ms, n))]
    return rows, ts


def by_key(emitted):
    return {(r["device"], r.get("winStart")): r for r in emitted}


def close_per_slot(ex, starts):
    """The LEGACY close: one extract_slot + one reset_slot dispatch per
    window, per-kid row decode — the reference the fused path must
    match exactly."""
    rows = []
    for s in sorted(starts):
        ow = ex._open.pop(s)
        if not ex.emit_changes:
            packed = np.asarray(ex._extract_slot(ex.state,
                                                 np.int32(ow.slot)))
            count, _sr, outs = lattice.unpack_extract_rows(ex.spec,
                                                           packed)
            for kid in np.nonzero(count > 0)[0]:
                row = ex._agg_row(int(kid), outs, int(kid), s)
                if row is not None:
                    rows.append(row)
        ex.state = ex._reset_slot(ex.state, np.int32(ow.slot))
        ex._no_close.discard(s)
    return rows


def run_pair(aggs, window, *, n=500, seed=1, having=None, post=None):
    """Drive a fused executor and a per-slot-patched twin through the
    same stream; return (fused rows, reference rows)."""
    fused = make_exec(aggs, window, having=having, post=post)
    ref = make_exec(aggs, window, having=having, post=post)
    ref._close_windows = lambda starts: close_per_slot(ref, starts)
    rows, ts = gen(n, seed=seed)
    out_f, out_r = [], []
    for i in range(0, n, 200):
        out_f.extend(fused.process(rows[i:i + 200], ts[i:i + 200]))
        out_r.extend(ref.process(rows[i:i + 200], ts[i:i + 200]))
    closer = [{"device": "d0", "temp": 0.0}], [BASE + 200_000]
    out_f.extend(fused.process(*closer))
    out_r.extend(ref.process(*closer))
    return out_f, out_r


def assert_rows_equal(out_f, out_r):
    assert len(out_f) == len(out_r) > 0
    kf, kr = by_key(out_f), by_key(out_r)
    assert set(kf) == set(kr)
    for key, want in kr.items():
        got = kf[key]
        assert set(got) == set(want), key
        for name, v in want.items():
            if isinstance(v, float):
                assert got[name] == pytest.approx(v, rel=1e-6), (key, name)
            else:
                assert got[name] == v, (key, name)


# ---- equivalence vs per-slot close -----------------------------------------

def test_batched_close_matches_per_slot_tumbling():
    out_f, out_r = run_pair([COUNT, SUM_T, MIN_T, AVG_T],
                            TumblingWindow(10_000, grace_ms=0))
    assert_rows_equal(out_f, out_r)


def test_batched_close_matches_per_slot_hopping_multi_due():
    # HOP(20s, 5s): a watermark jump closes SEVERAL windows in one
    # cycle — the case the fused kernel exists for
    out_f, out_r = run_pair([COUNT, SUM_T, UNIQ_T],
                            HoppingWindow(20_000, 5_000, grace_ms=0),
                            n=800, seed=2)
    assert_rows_equal(out_f, out_r)
    # the row-ordering contract also holds (window-major, key-ascending)
    assert [r.get("winStart") for r in out_f] == \
        [r.get("winStart") for r in out_r]


def test_batched_close_matches_with_having_and_projection():
    having = BinOp(">=", Col("cnt"), Lit(2))
    post = [("device", Col("device")),
            ("doubled", BinOp("*", Col("cnt"), Lit(2)))]
    out_f, out_r = run_pair([COUNT], TumblingWindow(10_000, grace_ms=0),
                            having=having, post=post, n=300, seed=3)
    assert_rows_equal(out_f, out_r)
    assert all("doubled" in r and "winStart" in r for r in out_f)


def test_host_only_projection_falls_back_per_row():
    # TO_UPPER is not vectorizable -> the columnwise path must fall
    # back to the per-row interpreter with identical results
    post = [("dev", UnOp("TO_UPPER", Col("device"))),
            ("cnt", Col("cnt"))]
    out_f, out_r = run_pair([COUNT], TumblingWindow(10_000, grace_ms=0),
                            post=post, n=200, seed=4)
    assert len(out_f) == len(out_r) > 0
    assert sorted((r["dev"], r["cnt"], r["winStart"]) for r in out_f) \
        == sorted((r["dev"], r["cnt"], r["winStart"]) for r in out_r)
    assert all(r["dev"].startswith("D") for r in out_f)


def test_topk_close_matches_per_slot():
    aggs = [COUNT, AggSpec(AggKind.TOPK, "top3", input=Col("temp"), k=3)]
    out_f, out_r = run_pair(aggs, TumblingWindow(10_000, grace_ms=0),
                            n=400, seed=5)
    assert len(out_f) == len(out_r) > 0
    kf, kr = by_key(out_f), by_key(out_r)
    assert set(kf) == set(kr)
    for key in kr:
        assert kf[key]["top3"] == pytest.approx(kr[key]["top3"]), key


# ---- dispatch accounting ----------------------------------------------------

def test_close_cycle_is_one_dispatch_one_fetch():
    # TUMBLE(10s) GRACE 20s keeps three windows open at once; advancing
    # the watermark makes all three due in ONE close_due_windows cycle —
    # which must cost exactly one kernel dispatch + one fetch
    ex = make_exec([COUNT, SUM_T], TumblingWindow(10_000,
                                                  grace_ms=20_000))
    rows, ts = gen(300, span_ms=25_000, seed=6)
    assert ex.process(rows, ts) == []  # grace holds everything open
    assert len(ex._open) == 3
    before = dict(ex.close_stats)
    ex.watermark_abs = BASE + 100_000
    out = ex.close_due_windows()
    assert len({r["winStart"] for r in out}) == 3
    assert ex.close_stats["close_cycles"] == before["close_cycles"] + 1
    assert ex.close_stats["close_dispatches"] == \
        before["close_dispatches"] + 1
    assert ex.close_stats["close_fetches"] == before["close_fetches"] + 1
    # a processed closer (inside the slot horizon) also costs one
    # dispatch per cycle end-to-end
    before = dict(ex.close_stats)
    ex.process(*rows_of(("d0", 1.0, 101_000)))
    assert ex.close_stats["close_dispatches"] - \
        before["close_dispatches"] == \
        ex.close_stats["close_cycles"] - before["close_cycles"]


def test_deferred_close_fetches_once_per_shape():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0))
    ex.defer_close_decode = True
    ex.process(*rows_of(("a", 1.0, 0)))
    assert ex.process(*rows_of(("a", 1.0, 12_000))) == []  # deferred
    assert ex.process(*rows_of(("a", 1.0, 25_000))) == []
    assert len(ex._pending_closes) == 2
    before = ex.close_stats["close_fetches"]
    out = ex.drain_closed()
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 1
    assert got[("a", BASE + 10_000)]["cnt"] == 1
    # same buffer shape -> ONE stacked fetch drains both cycles
    assert ex.close_stats["close_fetches"] == before + 1
    assert ex._pending_closes == []


def test_deferred_close_grow_keys_between_closes():
    # grow_keys between two deferred closes changes the packed K dim;
    # the drain must group by shape and decode both correctly
    ex = make_exec([COUNT, SUM_T], TumblingWindow(10_000, grace_ms=0),
                   initial_keys=8)
    ex.defer_close_decode = True
    rows, ts = rows_of(("a", 1.0, 0), ("b", 2.0, 100))
    ex.process(rows, ts)
    ex.process(*rows_of(("c", 1.0, 12_000)))  # closes w0 (deferred)
    grow_rows = [{"device": f"g{i}", "temp": 1.0} for i in range(40)]
    ex.process(grow_rows, [BASE + 13_000 + i for i in range(40)])
    assert ex.spec.n_keys > 8  # grew between the deferred closes
    ex.process(*rows_of(("c", 1.0, 26_000)))  # closes w1 (deferred)
    out = ex.drain_closed()
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 1
    assert got[("a", BASE)]["total"] == pytest.approx(1.0)
    assert got[("b", BASE)]["total"] == pytest.approx(2.0)
    assert got[("c", BASE + 10_000)]["cnt"] == 1
    assert sum(1 for r in out if r["winStart"] == BASE + 10_000) == 41


def test_emit_changes_close_resets_without_fetch():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0),
                   emit_changes=True)
    out = ex.process(*rows_of(("a", 1.0, 0), ("a", 1.0, 100)))
    assert out[0]["cnt"] == 2
    before = dict(ex.close_stats)
    ex.process(*rows_of(("a", 1.0, 12_000)))  # closes w0 silently
    assert ex.close_stats["close_dispatches"] == \
        before["close_dispatches"] + 1
    assert ex.close_stats["close_fetches"] == before["close_fetches"]
    # the reset really happened: a late-window peek shows only w1
    got = by_key(ex.peek())
    assert ("a", BASE) not in got
    assert got[("a", BASE + 10_000)]["cnt"] == 1


# ---- batched peek -----------------------------------------------------------

def test_peek_all_open_windows_single_dispatch():
    ex = make_exec([COUNT, SUM_T], HoppingWindow(20_000, 5_000,
                                                 grace_ms=0))
    rows, ts = gen(300, span_ms=18_000, seed=7)
    ex.process(rows, ts)
    assert len(ex._open) >= 4
    calls = []
    orig = ex._extract_slots

    def counting(state, slots):
        calls.append(len(slots))
        return orig(state, slots)

    ex._extract_slots = counting
    got = by_key(ex.peek())
    assert len(calls) == 1  # ONE batched dispatch for every open window
    # reference: per-window legacy extract
    want = {}
    for s in sorted(ex._open):
        ow = ex._open[s]
        packed = np.asarray(ex._extract_slot(ex.state, np.int32(ow.slot)))
        count, _sr, outs = lattice.unpack_extract_rows(ex.spec, packed)
        for kid in np.nonzero(count > 0)[0]:
            row = ex._agg_row(int(kid), outs, int(kid), s)
            if row is not None:
                want[(row["device"], row["winStart"])] = row
    assert set(got) == set(want)
    for key, w in want.items():
        assert got[key]["cnt"] == w["cnt"]
        assert got[key]["total"] == pytest.approx(w["total"], rel=1e-6)


def test_windowless_peek_matches_changes():
    ex = make_exec([COUNT, SUM_T], window=None, emit_changes=True)
    ex.process(*rows_of(("a", 1.0, 0), ("b", 2.0, 50), ("a", 3.0, 60)))
    got = {r["device"]: r for r in ex.peek()}
    assert got["a"]["cnt"] == 2 and got["a"]["total"] == pytest.approx(4.0)
    assert got["b"]["cnt"] == 1


# ---- columnar emission ------------------------------------------------------

def test_close_emits_columnar_batch_to_the_wire():
    ex = make_exec([COUNT, SUM_T], TumblingWindow(10_000,
                                                  grace_ms=20_000))
    rows, ts = gen(200, span_ms=25_000, seed=8)
    assert ex.process(rows, ts) == []  # grace holds everything open
    ex.watermark_abs = BASE + 100_000
    closed = ex.close_due_windows()
    assert isinstance(closed, ColumnarEmit)  # stayed columnar
    assert len({r["winStart"] for r in closed}) == 3  # one fused cycle
    # one columnar wire record straight from the columns
    payload = rows_to_payload(closed, 123)
    assert payload is not None
    ts_dec, cols_dec = decode_columnar(payload)
    wire_rows = to_rows(ts_dec, cols_dec)
    assert len(wire_rows) == len(closed)
    legacy = closed.rows()
    for w, l in zip(wire_rows, legacy):
        assert set(w) == set(l)
        assert w["device"] == l["device"]
        assert w["cnt"] == l["cnt"]
        assert w["winStart"] == l["winStart"]
        assert w["total"] == pytest.approx(l["total"], rel=1e-6)
    # Sequence protocol: len / index / iterate / extend into a list
    acc = []
    acc.extend(closed)
    assert acc == legacy and closed[0] == legacy[0]


def test_extend_rows_keeps_lone_batch_columnar():
    ce = ColumnarEmit({"a": np.asarray([1, 2])}, 2)
    assert extend_rows(None, ce) is ce
    assert extend_rows([], ce) is ce
    mixed = extend_rows(ce, [{"a": 3}])
    assert isinstance(mixed, list)
    assert mixed == [{"a": 1}, {"a": 2}, {"a": 3}]
    assert extend_rows(ce, []) is ce


def test_topk_batch_falls_back_to_row_records():
    aggs = [AggSpec(AggKind.TOPK, "top2", input=Col("temp"), k=2)]
    ex = make_exec(aggs, TumblingWindow(10_000, grace_ms=0))
    ex.process(*rows_of(("a", 1.0, 0), ("a", 5.0, 10), ("a", 3.0, 20)))
    closed = ex.process(*rows_of(("a", 0.0, 15_000)))
    assert isinstance(closed, ColumnarEmit)
    assert rows_to_payload(closed, 1) is None  # lists -> per-row
    assert closed[0]["top2"] == [5.0, 3.0]


# ---- session windows stay unaffected ---------------------------------------

def test_session_close_and_peek_unchanged():
    from hstream_tpu.engine.plan import AggregateNode as AN
    from hstream_tpu.engine.session import SessionExecutor
    from hstream_tpu.engine.window import SessionWindow

    node = AN(child=SourceNode("s", SCHEMA), group_keys=[Col("device")],
              window=SessionWindow(5_000, grace_ms=0),
              aggs=[COUNT, SUM_T])
    ex = SessionExecutor(node, SCHEMA, emit_changes=False)
    ex.process(*rows_of(("a", 1.0, 0), ("a", 2.0, 1_000)))
    live = ex.peek()
    assert live and live[0]["cnt"] == 2
    out = ex.process(*rows_of(("a", 7.0, 60_000)))  # closes the session
    assert len(out) == 1
    assert out[0]["cnt"] == 2 and out[0]["total"] == pytest.approx(3.0)


# ---- sharded executor -------------------------------------------------------

def _has_shard_map() -> bool:
    import jax

    return hasattr(jax, "shard_map")


@pytest.mark.skipif(not _has_shard_map(),
                    reason="jax.shard_map unavailable in this jax")
def test_sharded_batched_close_matches_single_chip():
    from hstream_tpu.parallel import ShardedQueryExecutor, make_mesh

    mesh = make_mesh(n_data=4, n_key=2)
    window = HoppingWindow(20_000, 5_000, grace_ms=0)
    node = AggregateNode(child=SourceNode("s", SCHEMA),
                         group_keys=[Col("device")], window=window,
                         aggs=[COUNT, SUM_T, MIN_T])
    ref = QueryExecutor(node, SCHEMA, emit_changes=False,
                        initial_keys=16, batch_capacity=256)
    sh = ShardedQueryExecutor(node, SCHEMA, mesh=mesh,
                              emit_changes=False, initial_keys=16,
                              batch_capacity=256)
    rows, ts = gen(500, n_keys=13, span_ms=22_000, seed=9)
    out_ref, out_sh = [], []
    for i in range(0, 500, 200):
        out_ref.extend(ref.process(rows[i:i + 200], ts[i:i + 200]))
        out_sh.extend(sh.process(rows[i:i + 200], ts[i:i + 200]))
    before = dict(sh.close_stats)
    closer = [{"device": "d0", "temp": 0.0}], [BASE + 200_000]
    out_ref.extend(ref.process(*closer))
    out_sh.extend(sh.process(*closer))
    # the multi-window cycle was ONE dispatch + ONE fetch on the mesh too
    assert sh.close_stats["close_cycles"] == before["close_cycles"] + 1
    assert sh.close_stats["close_dispatches"] == \
        before["close_dispatches"] + 1
    assert sh.close_stats["close_fetches"] == before["close_fetches"] + 1
    assert_rows_equal(out_sh, out_ref)
    # batched peek parity (both should be empty after the big closer,
    # bar the closer's own window)
    assert {(r["device"], r["winStart"]) for r in sh.peek()} == \
        {(r["device"], r["winStart"]) for r in ref.peek()}
