"""Flow-control subsystem: hierarchical quotas, overload shedding,
credit-based delivery, client retry, quota persistence.

Quota/rate tests run on a fake clock — zero wall-clock sleeps; the
credit-delivery test drives a real dispatcher thread with deadline
polls (helpers-style), no fixed sleeps on the assert path.
"""

import threading
import time

import grpc
import pytest

from hstream_tpu.client.retry import RetryPolicy, retry_after_ms_from_error
from hstream_tpu.common.errors import ResourceExhausted
from hstream_tpu.flow import (
    ADMIT,
    DEFER,
    REJECT,
    CreditWindow,
    FlowGovernor,
    OverloadDetector,
    Quota,
    QuotaTree,
    TokenBucket,
    tenant_of,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---- token bucket -----------------------------------------------------------


def test_bucket_burst_then_sustained_rate():
    clk = FakeClock()
    b = TokenBucket(100.0, 100.0, clock=clk)
    # the whole burst is admissible immediately...
    assert b.try_take(100.0) == 0.0
    # ...then the bucket is empty and reports the accrual wait
    wait = b.try_take(10.0)
    assert wait == pytest.approx(0.1)
    clk.advance(0.5)  # 50 tokens accrue
    assert b.try_take(50.0) == 0.0
    assert b.try_take(1.0) > 0.0


def test_bucket_debt_converges_on_rate():
    """Unconditional take (charge-after-read) goes into debt; refills
    repay it before anything else is admitted."""
    clk = FakeClock()
    b = TokenBucket(10.0, 10.0, clock=clk)
    b.take(30.0)  # 20 tokens of debt
    assert b.try_take(1.0) > 0.0
    clk.advance(2.0)  # exactly repays the debt
    assert b.tokens == pytest.approx(0.0)
    clk.advance(0.1)
    assert b.try_take(1.0) == 0.0


# ---- quota tree -------------------------------------------------------------


def test_tenant_of():
    assert tenant_of("acme/orders") == "acme"
    assert tenant_of("acme.events") == "acme"
    assert tenant_of("acme.a/b") == "acme"
    assert tenant_of("plain") is None


def test_quota_tree_stream_and_tenant_levels():
    clk = FakeClock()
    tree = QuotaTree(clk)
    tree.set("stream/acme.a", Quota(records_per_s=10, burst_records=10))
    tree.set("tenant/acme", Quota(records_per_s=15, burst_records=15))
    # stream cap binds first
    assert tree.admit_append("acme.a", 10, 0) == 0.0
    assert tree.admit_append("acme.a", 1, 0) > 0.0
    # the sibling stream has no stream-level quota but shares the tenant
    # budget, of which acme.a already consumed 10
    assert tree.admit_append("acme.b", 5, 0) == 0.0
    assert tree.admit_append("acme.b", 1, 0) > 0.0
    # an unrelated tenant is untouched
    assert tree.admit_append("other.x", 100, 0) == 0.0


def test_quota_tree_refusal_consumes_nothing():
    clk = FakeClock()
    tree = QuotaTree(clk)
    tree.set("stream/s", Quota(records_per_s=10, burst_records=10,
                               bytes_per_s=100, burst_bytes=100))
    assert tree.admit_append("s", 1, 100) == 0.0  # drain bytes bucket
    # bytes level refuses -> the records bucket must not be charged
    assert tree.admit_append("s", 1, 50) > 0.0
    assert tree.admit_append("s", 9, 0) == 0.0  # 9 record tokens intact


def test_offered_10x_admitted_at_quota_rate():
    """Acceptance bar: 10xR offered load admits at R (+/-10%), rejects
    carry retry-after hints. Fake clock, zero sleeps."""
    clk = FakeClock()
    gov = FlowGovernor(clock=clk)
    R = 100.0
    gov.quotas.set("stream/s", Quota(records_per_s=R, burst_records=R))
    gov._recompute_active()
    assert gov.active
    admitted = 0
    hints = []
    seconds = 20
    per_tick = 10  # 10ms ticks x 10 records = 1000/s offered = 10xR
    for _ in range(seconds * 100):
        clk.advance(0.01)
        try:
            gov.admit_append("s", per_tick, 0)
            admitted += per_tick
        except ResourceExhausted as e:
            assert e.retry_after_ms is not None and e.retry_after_ms >= 1
            hints.append(e.retry_after_ms)
    expected = R * seconds
    # +burst_records of slack for the initial full bucket
    assert 0.9 * expected <= admitted <= 1.1 * expected + R
    assert hints, "over-quota offered load must produce refusals"


def test_quota_rejects_non_positive_rates():
    with pytest.raises(ValueError):
        Quota(records_per_s=0)
    with pytest.raises(ValueError):
        Quota(bytes_per_s=-5)
    with pytest.raises(ValueError):
        Quota.from_json({"records_per_s": 0})
    with pytest.raises(ValueError):
        Quota(burst_records=10)  # burst without rate enforces nothing
    with pytest.raises(ValueError):
        Quota()  # all-None quota is a no-op, not a limit


def test_oversize_batch_admits_into_debt_with_truthful_hint():
    """A batch larger than the burst admits at a full bucket (going
    into debt) — the retry-after hint is always achievable, never a
    forever-retry trap."""
    clk = FakeClock()
    gov = FlowGovernor(clock=clk)
    gov.set_quota("stream/s", Quota(records_per_s=100, burst_records=100))
    gov.admit_append("s", 150, 0)  # full bucket: admitted, 50 in debt
    with pytest.raises(ResourceExhausted) as ei:
        gov.admit_append("s", 150, 0)
    # waiting out the hint makes the SAME request admissible
    clk.advance(ei.value.retry_after_ms / 1000.0)
    gov.admit_append("s", 150, 0)
    # and the next oversize batch waits again (debt repaid at the rate)
    wait = gov.quotas.admit_append("s", 150, 0)
    assert 0 < wait <= 60.0


def test_quota_unset_deactivates_hot_path():
    gov = FlowGovernor(clock=FakeClock())
    assert not gov.active
    gov.set_quota("stream/s", Quota(records_per_s=5))
    assert gov.active
    gov.unset_quota("stream/s")
    assert not gov.active


# ---- overload detector ------------------------------------------------------


def test_overload_detector_transitions_from_pipeline_signals():
    det = OverloadDetector()
    assert det.level == ADMIT
    # synthetic pipeline-stage occupancy ramps: EWMA needs sustained
    # high samples (one spike is not overload)
    det.note("pipeline_occupancy", 0.99)
    assert det.level == ADMIT  # ewma at ~0.5 after one sample
    for _ in range(6):
        det.note("pipeline_occupancy", 0.99)
    assert det.level == REJECT
    # recovery requires sustained low samples too
    det.note("pipeline_occupancy", 0.0)
    assert det.level in (DEFER, REJECT)
    for _ in range(8):
        det.note("pipeline_occupancy", 0.0)
    assert det.level == ADMIT


def test_overload_detector_rejects_unknown_signal():
    with pytest.raises(KeyError):
        OverloadDetector().note("nope", 1.0)


def test_idle_sources_do_not_mask_overloaded_one():
    """Per-source max aggregation: three idle subscriptions feeding
    zeros cannot average away one subscription's critical backlog."""
    det = OverloadDetector()
    for _ in range(10):
        det.note("sub_backlog", 150_000.0, source="hot")
        for idle in ("a", "b", "c"):
            det.note("sub_backlog", 0.0, source=idle)
    assert det.effective_level() == REJECT


def test_stale_signal_expires_per_signal():
    """A producer that died at critical (e.g. a deleted subscription's
    backlog feed) must expire on its own clock — other signals staying
    fresh and healthy cannot pin the shed level."""
    clk = FakeClock()
    det = OverloadDetector(clock=clk, stale_after_s=10.0)
    for _ in range(10):
        det.note("sub_backlog", 500_000.0)
    assert det.effective_level() == REJECT
    # the backlog feed dies; a healthy query keeps feeding low latency
    for _ in range(30):
        clk.advance(1.0)
        det.note("step_latency_ms", 1.0)
    assert det.effective_level() == ADMIT  # stale critical expired
    # and a revived feed counts again
    for _ in range(10):
        det.note("sub_backlog", 500_000.0)
    assert det.effective_level() == REJECT


def test_shed_ladder_background_before_user():
    gov = FlowGovernor(clock=FakeClock())
    det = gov.overload
    # DEFER: background sheds, user appends flow
    for _ in range(8):
        det.note("step_latency_ms", 400.0)
    assert det.level == DEFER and gov.active
    assert gov.admit_background("connector") > 0.0
    gov.admit_append("s", 1, 10)  # no quota, not rejected at DEFER
    # REJECT: user appends refused with a retry-after hint
    for _ in range(8):
        det.note("step_latency_ms", 10_000.0)
    assert det.level == REJECT
    with pytest.raises(ResourceExhausted) as ei:
        gov.admit_append("s", 1, 10)
    assert ei.value.retry_after_ms is not None
    assert gov.admit_background("connector") > 0.0
    assert gov.shed_by_class["user"] == 1
    assert gov.shed_by_class["background"] == 2


# ---- client retry -----------------------------------------------------------


class FakeExhausted(grpc.RpcError):
    def __init__(self, retry_after_ms=None):
        self._ra = retry_after_ms

    def code(self):
        return grpc.StatusCode.RESOURCE_EXHAUSTED

    def details(self):
        if self._ra is None:
            return "quota exceeded"
        return f"quota exceeded (retry_after_ms={self._ra})"

    def trailing_metadata(self):
        if self._ra is None:
            return ()
        return (("retry-after-ms", str(self._ra)),)


def test_retry_after_parsing_metadata_and_text():
    assert retry_after_ms_from_error(FakeExhausted(120)) == 120

    class TextOnly(FakeExhausted):
        def trailing_metadata(self):
            return ()

    assert retry_after_ms_from_error(TextOnly(77)) == 77
    assert retry_after_ms_from_error(FakeExhausted()) is None


def test_client_retry_converges_on_quota_without_herd():
    """N clients against one fake-clock governor: every client's call
    eventually lands, total admissions track the quota, and the jittered
    delays are spread (no thundering herd). Zero wall-clock sleeps."""
    import random

    clk = FakeClock()
    lock = threading.Lock()  # governor is shared; test is single-threaded
    gov = FlowGovernor(clock=clk)
    R = 50.0
    gov.set_quota("stream/s", Quota(records_per_s=R, burst_records=R))

    def server_append(n):
        with lock:
            try:
                gov.admit_append("s", n, 0)
            except ResourceExhausted as e:
                raise FakeExhausted(e.retry_after_ms)

    delays: list[float] = []

    def make_client(seed):
        def fake_sleep(s):
            delays.append(s)
            clk.advance(s)

        return RetryPolicy(attempts=10, sleep=fake_sleep,
                           rng=random.Random(seed))

    clients = [make_client(i) for i in range(20)]
    done = 0
    for round_i in range(5):
        for c in clients:
            c.call(server_append, 5)  # raises if it cannot converge
            done += 1
    assert done == 100
    total_retries = sum(c.retries for c in clients)
    assert total_retries > 0, "10x load must have caused retries"
    # jitter: the backoff delays must not collapse onto one value
    assert len({round(d, 6) for d in delays}) > len(delays) // 2


# ---- credit-based delivery --------------------------------------------------


def test_credit_window_take_refill():
    w = CreditWindow(8)
    assert w.take_up_to(5) == 5
    assert w.take_up_to(5) == 3
    assert w.take_up_to(1, timeout=0.01) == 0
    w.refill(4)
    assert w.take_up_to(100) == 4
    w.refill(1000)  # capped at the window
    assert w.available == 8


def _poll(predicate, timeout=10.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def test_stalled_subscriber_bounded_by_credit_window():
    """A consumer that never acks holds at most its credit window of
    undelivered records server-side; acks resume ordered delivery."""
    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.server.context import ServerContext
    from hstream_tpu.store import open_store

    WINDOW = 8
    N = 50
    ctx = ServerContext(open_store("mem://"), credit_window=WINDOW)
    try:
        ctx.streams.create_stream("credsrc")
        logid = ctx.streams.get_logid("credsrc")
        payloads = [rec.build_record({"i": i}).SerializeToString()
                    for i in range(N)]
        for p in payloads:  # one record per batch: exact credit math
            ctx.store.append(logid, p)
        meta = pb.Subscription(subscription_id="credsub",
                               stream_name="credsrc")
        rt = ctx.subscriptions.create(ctx, meta)
        consumer = rt.register_consumer("slow")

        def queued_records():
            with consumer.queue.mutex:
                return sum(len(b) for b in consumer.queue.queue)

        # the dispatcher delivers until credits run out, then pauses
        assert _poll(lambda: queued_records() == WINDOW)
        assert not _poll(lambda: queued_records() > WINDOW, timeout=0.5)
        assert ctx.stats.stream_stat_get(
            "delivery_credit_waits", "credsrc") > 0

        # drain + ack in order; delivery resumes and stays ordered
        seen: list[int] = []
        while len(seen) < N:
            assert _poll(lambda: not consumer.queue.empty()), \
                f"stalled after {len(seen)} records"
            batch = consumer.queue.get_nowait()
            ids = []
            for rid, payload in batch:
                r = rec.parse_record(payload)
                seen.append(rec.record_to_dict(r)["i"])
                ids.append(rid)
            rt.ack(ids, consumer=consumer)
        assert seen == list(range(N))
        assert rt.committed_lsn > 0
    finally:
        ctx.shutdown()


def test_latest_subscriber_reports_zero_backlog():
    """A fresh LATEST subscriber on a long stream has nothing
    outstanding — it must not feed the whole log as phantom backlog
    into the overload detector (which would shed user appends)."""
    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.server.context import ServerContext
    from hstream_tpu.store import open_store

    ctx = ServerContext(open_store("mem://"))
    try:
        ctx.streams.create_stream("longlog")
        logid = ctx.streams.get_logid("longlog")
        for i in range(20):
            ctx.store.append(
                logid, rec.build_record({"i": i}).SerializeToString())
        meta = pb.Subscription(
            subscription_id="latest1", stream_name="longlog",
            offset=pb.SubscriptionOffset(special_offset=1))  # LATEST
        rt = ctx.subscriptions.create(ctx, meta)
        rt.reader()  # seeds committed from the actual start position
        tail = ctx.store.tail_lsn(logid)
        assert rt.committed_lsn >= tail  # lag == 0, not 20
    finally:
        ctx.shutdown()


def test_unary_acks_refill_streaming_consumer_credits():
    """Acks arriving without a consumer (the unary Acknowledge RPC)
    still refill delivery credits — a client mixing StreamingFetch
    delivery with unary acks must not stall at window exhaustion."""
    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.server.context import ServerContext
    from hstream_tpu.store import open_store

    WINDOW = 8
    N = 3 * WINDOW
    ctx = ServerContext(open_store("mem://"), credit_window=WINDOW)
    try:
        ctx.streams.create_stream("uack")
        logid = ctx.streams.get_logid("uack")
        for i in range(N):
            ctx.store.append(
                logid, rec.build_record({"i": i}).SerializeToString())
        rt = ctx.subscriptions.create(
            ctx, pb.Subscription(subscription_id="uacksub",
                                 stream_name="uack"))
        consumer = rt.register_consumer("mixed")
        seen = 0
        while seen < N:
            assert _poll(lambda: not consumer.queue.empty()), \
                f"stalled after {seen} records (credits not refilled?)"
            batch = consumer.queue.get_nowait()
            seen += len(batch)
            rt.ack([rid for rid, _ in batch])  # unary path: no consumer
        assert seen == N
    finally:
        ctx.shutdown()


# ---- persistence ------------------------------------------------------------


def test_quota_persists_across_server_restart(tmp_path):
    from hstream_tpu.server.context import ServerContext
    from hstream_tpu.store import open_store

    path = str(tmp_path / "store")
    ctx = ServerContext(open_store(path))
    ctx.flow.set_quota("stream/s",
                       Quota(records_per_s=5, burst_records=5))
    ctx.flow.set_quota("tenant/acme", Quota(bytes_per_s=1000))
    ctx.shutdown()

    ctx2 = ServerContext(open_store(path))
    try:
        q = ctx2.flow.get_quota("stream/s")
        assert q is not None and q.records_per_s == 5.0
        assert ctx2.flow.get_quota("tenant/acme").bytes_per_s == 1000.0
        assert ctx2.flow.active
        # and it is enforced: the 5-record burst admits, the 6th refuses
        ctx2.flow.admit_append("s", 5, 0)
        with pytest.raises(ResourceExhausted):
            ctx2.flow.admit_append("s", 1, 0)
        # unset survives too
        ctx2.flow.unset_quota("tenant/acme")
    finally:
        ctx2.shutdown()

    ctx3 = ServerContext(open_store(path))
    try:
        assert ctx3.flow.get_quota("tenant/acme") is None
        assert ctx3.flow.get_quota("stream/s") is not None
    finally:
        ctx3.shutdown()


# ---- stats shard retirement (satellite regression) --------------------------


def test_stats_shards_bounded_across_thread_churn():
    """Counter shards of exited threads fold into a retired aggregate
    on read: totals exact, shard list bounded."""
    from hstream_tpu.stats import StatsHolder

    h = StatsHolder()
    h.stream_stat_add("append_total", "s", 1)  # main-thread shard

    def bump():
        h.stream_stat_add("append_total", "s", 2)

    for _ in range(40):
        t = threading.Thread(target=bump)
        t.start()
        t.join()
    assert h.stream_stat_get("append_total", "s") == 1 + 40 * 2
    assert len(h._shards) <= 2  # main + at most one straggler
    # getall folds the same way
    assert h.stream_stat_getall("append_total") == {"s": 81}
