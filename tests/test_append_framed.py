"""Framed columnar append path (ISSUE 12): wire-format codec, the
sharded append front, server equivalence against the protobuf Append
path (same rows, same record ids), the streaming variant, and the
malformed/torn/overlong-frame refusal contract (typed INVALID_ARGUMENT,
never a partial ingest)."""

import time

import grpc
import numpy as np
import pytest

from hstream_tpu.common import colframe, columnar
from hstream_tpu.common import records as rec
from hstream_tpu.common.errors import InvalidFrame
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.client.producer import ColumnarProducer, encode_batch
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.appendfront import AppendFront
from hstream_tpu.server.main import serve
from hstream_tpu.store.memstore import MemLogStore

from helpers import wait_attached

BASE = 1_700_000_000_000


# ---- frame codec ----------------------------------------------------------

def test_frame_roundtrip():
    payload = columnar.encode_columnar(
        BASE + np.arange(4, dtype=np.int64),
        {"k": ["a", "b", "a", "c"], "v": np.arange(4, dtype=np.float32)})
    frame = colframe.encode_frame(payload)
    assert len(frame) == colframe.FRAME_HEADER_LEN + len(payload)
    body = colframe.open_frame(frame)
    assert bytes(body) == payload
    # open_block validates the embedded columnar bounds too
    body2, n, last_ts = colframe.open_block(frame)
    assert (n, last_ts) == (4, BASE + 3)


@pytest.mark.parametrize("mutate, msg", [
    (lambda f: f[:8], "shorter than"),                      # short header
    (lambda f: b"XXXX" + f[4:], "bad frame magic"),         # magic
    (lambda f: f[:4] + bytes([99]) + f[5:], "version"),     # version
    (lambda f: f[:-3], "truncated"),                        # truncated
    (lambda f: f + b"xx", "overlong"),                      # overlong
    (lambda f: f[:-1] + bytes([f[-1] ^ 0xFF]), "CRC"),      # corrupt
])
def test_frame_refusals(mutate, msg):
    frame = colframe.encode_frame(columnar.encode_columnar(
        np.array([BASE], np.int64), {"k": ["a"]}))
    with pytest.raises(InvalidFrame, match=msg):
        colframe.open_frame(mutate(frame))


def test_frame_torn_bytes_refused_deterministically():
    """The faultinject torn machinery (seeded mid-payload truncation)
    against the frame door: every torn shape is a typed refusal."""
    payload = columnar.encode_columnar(
        BASE + np.arange(64, dtype=np.int64),
        {"k": [f"k{i % 5}" for i in range(64)],
         "v": np.arange(64, dtype=np.float32)})
    frame = colframe.encode_frame(payload)
    for seed in range(8):
        FAULTS.arm("test.frame.torn", f"torn:1:{seed}")
        try:
            torn = FAULTS.mutate("test.frame.torn", frame)
        finally:
            FAULTS.disarm("test.frame.torn")
        assert len(torn) < len(frame)
        with pytest.raises(InvalidFrame):
            colframe.open_frame(torn)


def test_forged_inner_block_refused():
    """A well-framed block whose columnar header lies about its sizes
    must be refused at the door (open_block), not deep in a task."""
    good = columnar.encode_columnar(
        BASE + np.arange(8, dtype=np.int64), {"v": np.arange(8)})
    # truncate the block body but reframe with a VALID frame header:
    # only the inner columnar bounds check can catch this
    forged = colframe.encode_frame(good[:-8])
    with pytest.raises(InvalidFrame, match="columnar"):
        colframe.open_block(forged)
    # an empty block (n=0) is refused too — nothing to append
    empty = colframe.encode_frame(columnar.encode_columnar(
        np.array([], np.int64), {}))
    with pytest.raises(InvalidFrame, match="empty"):
        colframe.open_block(empty)


# ---- null-mask wire extension ---------------------------------------------

def test_columnar_nulls_roundtrip():
    ts = BASE + np.arange(6, dtype=np.int64)
    cols = {"k": ["a", "b", "a", "b", "a", "b"],
            "v": np.arange(6, dtype=np.float32)}
    nulls = {"v": np.array([0, 1, 0, 0, 1, 0], np.bool_)}
    blob = columnar.encode_columnar(ts, cols, nulls=nulls)
    ts2, cols2, nulls2 = columnar.decode_columnar_nulls(blob)
    np.testing.assert_array_equal(ts2, ts)
    np.testing.assert_array_equal(nulls2["v"], nulls["v"])
    # legacy payloads (no masks) decode with nulls=None
    legacy = columnar.encode_columnar(ts, cols)
    _, _, n3 = columnar.decode_columnar_nulls(legacy)
    assert n3 is None
    # the 2-tuple decode stays stable for old callers
    ts4, cols4 = columnar.decode_columnar(blob)
    np.testing.assert_array_equal(ts4, ts)
    assert set(cols4) == {"k", "v"}
    # rows: masked cells are ABSENT like the per-record decode shape
    rows = columnar.to_rows(ts2, cols2, nulls2, drop_null=True)
    assert "v" not in rows[1] and rows[0]["v"] == 0.0


def test_columnar_nulls_bounds_checked():
    ts = BASE + np.arange(4, dtype=np.int64)
    blob = columnar.encode_columnar(
        ts, {"v": np.arange(4)},
        nulls={"v": np.array([1, 0, 0, 1], np.bool_)})
    # cut into the mask region: declared sizes no longer fit
    with pytest.raises(ValueError):
        columnar.decode_columnar_nulls(blob[:-2])
    with pytest.raises(ValueError):
        columnar.encode_columnar(
            ts, {"v": np.arange(4)},
            nulls={"missing": np.zeros(4, np.bool_)})
    with pytest.raises(ValueError):
        columnar.encode_columnar(
            ts, {"v": np.arange(4)},
            nulls={"v": np.zeros(3, np.bool_)})


# ---- record splice --------------------------------------------------------

def test_wrap_raw_record_parses_identically():
    payload = columnar.encode_columnar(
        BASE + np.arange(3, dtype=np.int64), {"v": np.arange(3)})
    spliced = rec.wrap_raw_record(payload, BASE + 2)
    reference = rec.build_record(payload, publish_time_ms=BASE + 2)
    got = rec.parse_record(spliced)
    assert got == reference
    assert got.header.flag == pb.RECORD_FLAG_RAW
    assert got.header.publish_time_ms == BASE + 2
    assert got.payload == payload


def test_record_bytes_stamps_batch_default_once():
    """The Append satellite: a record already carrying a timestamp is
    never mutated; one missing it gets the batch default — and both
    parse identically to the full SerializeToString path."""
    stamped = rec.build_record({"k": "a"}, publish_time_ms=BASE)
    unstamped = rec.build_record({"k": "b"})
    unstamped.header.publish_time_ms = 0
    assert rec.parse_record(rec.record_bytes(stamped, default_ts=123)) \
        == stamped
    got = rec.parse_record(rec.record_bytes(unstamped, default_ts=456))
    assert got.header.publish_time_ms == 456
    assert rec.record_to_dict(got) == {"k": "b"}
    # big payloads take the splice path: equivalence there too
    big = rec.build_record(b"\x00" * 100_000, key="kk",
                           attributes={"a": "1"}, publish_time_ms=BASE)
    assert rec.parse_record(rec.record_bytes(big, default_ts=1)) == big


def test_peek_columnar_payload():
    """The zero-copy read-side peek: columnar records yield their
    payload view with no protobuf parse; everything else returns None
    (full-parse fallback)."""
    payload = columnar.encode_columnar(
        BASE + np.arange(4, dtype=np.int64), {"v": np.arange(4)})
    for data in (rec.wrap_raw_record(payload, BASE),
                 rec.build_columnar_record(
                     BASE + np.arange(4, dtype=np.int64),
                     {"v": np.arange(4)}).SerializeToString(),
                 rec.build_record(payload, key="k",
                                  attributes={"a": "b"},
                                  publish_time_ms=BASE)
                 .SerializeToString()):
        v = rec.peek_columnar_payload(data)
        assert v is not None
        assert columnar.is_columnar(v)
    # JSON records, raw non-columnar records, garbage: None
    assert rec.peek_columnar_payload(
        rec.build_record({"k": "a"}).SerializeToString()) is None
    assert rec.peek_columnar_payload(
        rec.build_record(b"opaque").SerializeToString()) is None
    assert rec.peek_columnar_payload(b"\x99garbage") is None
    # a JSON-flagged record whose payload bytes open with the magic
    # must NOT masquerade as a column batch (flag check)
    forged = rec.build_record({"k": "a"})
    forged.payload = columnar.MAGIC + forged.payload
    assert rec.peek_columnar_payload(forged.SerializeToString()) is None


# ---- append front ---------------------------------------------------------

def test_append_front_per_log_fifo_and_errors():
    store = MemLogStore()
    store.create_log(1)
    store.create_log(2)
    front = AppendFront(store, lanes=2)
    try:
        futs = [front.submit(1 + (i % 2), [b"p%d" % i]) for i in range(20)]
        lsns = [f.result(timeout=5) for f in futs]
        # per-log order: each log's lsns are strictly increasing
        assert lsns[0::2] == sorted(lsns[0::2])
        assert lsns[1::2] == sorted(lsns[1::2])
        # an unknown log resolves to the store's exception, the lane
        # survives for the next submission
        bad = front.submit(999, [b"x"])
        with pytest.raises(Exception):
            bad.result(timeout=5)
        ok = front.submit(1, [b"tail"])
        assert ok.result(timeout=5) > 0
        st = front.stats()
        assert st["submitted"] == 22 and st["in_flight"] == 0
    finally:
        front.close()


# ---- server: equivalence + streaming + refusals ---------------------------

@pytest.fixture(scope="module")
def server_stub():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    yield stub, ctx
    channel.close()
    server.stop(grace=1)
    ctx.shutdown()


def _mk_batches(n_batches, n, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = BASE + b * 1000 + np.sort(rng.integers(0, 1000, n)) \
            .astype(np.int64)
        cols = {"device": [f"d{i}" for i in
                           rng.integers(0, 7, n).tolist()],
                "temp": rng.normal(20, 5, n).astype(np.float32)}
        out.append((ts, cols))
    return out


def _view_rows(stub, view, pred, timeout=30):
    rows = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=f"SELECT * FROM {view};"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        if pred(rows):
            break
        time.sleep(0.2)
    return rows


def _mk_view(stub, ctx, view, src):
    stub.CreateStream(pb.Stream(stream_name=src))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text=f"CREATE VIEW {view} AS SELECT device, COUNT(*) AS c, "
                  f"SUM(temp) AS s FROM {src} "
                  f"GROUP BY device, TUMBLING (INTERVAL 10 SECOND) "
                  f"GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, f"view-{view}")


def test_framed_equals_protobuf_append(server_stub):
    """THE equivalence contract: the same micro-batches through the
    protobuf Append path and the framed AppendColumnar path land the
    same rows (byte-identical view results) under the same record ids
    (fresh streams -> same LSN sequence)."""
    stub, ctx = server_stub
    _mk_view(stub, ctx, "eqpb", "eqsrc_pb")
    _mk_view(stub, ctx, "eqfr", "eqsrc_fr")
    batches = _mk_batches(5, 512)
    closer = (np.array([BASE + 60_000], np.int64),
              {"device": ["zz"], "temp": np.array([1.0], np.float32)})
    pb_ids, fr_ids = [], []
    for ts, cols in batches + [closer]:
        req = pb.AppendRequest(stream_name="eqsrc_pb")
        req.records.append(rec.build_columnar_record(ts, cols))
        r = stub.Append(req)
        pb_ids.extend((i.batch_id, i.batch_index) for i in r.record_ids)
    for ts, cols in batches + [closer]:
        r = stub.AppendColumnar(pb.AppendColumnarRequest(
            stream_name="eqsrc_fr", blocks=[encode_batch(ts, cols)]))
        fr_ids.extend((i.batch_id, i.batch_index) for i in r.record_ids)
        assert r.rows == len(ts)
    assert fr_ids == pb_ids

    def done(rows):
        return sum(r["c"] for r in rows
                   if r.get("winStart", -1) >= 0) >= 5 * 512

    rows_pb = _view_rows(stub, "eqpb", done)
    rows_fr = _view_rows(stub, "eqfr", done)
    key = lambda r: (r.get("winStart"), r.get("device"))  # noqa: E731
    assert sorted(rows_pb, key=key) == sorted(rows_fr, key=key)
    # the 5 data batches, excluding the closer's own window
    assert sum(r["c"] for r in rows_pb
               if r.get("winStart") < BASE + 60_000) == 5 * 512


def test_streaming_append_one_call_many_batches(server_stub):
    stub, ctx = server_stub
    _mk_view(stub, ctx, "stv", "stsrc")
    batches = _mk_batches(8, 256, seed=11)
    prod = ColumnarProducer(f"127.0.0.1:{ctx.port}", "stsrc")
    try:
        resp = prod.append_stream(iter(batches))
        assert resp.rows == 8 * 256
        assert len(resp.record_ids) == 8
        lsns = [i.batch_id for i in resp.record_ids]
        assert lsns == sorted(lsns)  # submission order preserved
        prod.append(np.array([BASE + 60_000], np.int64),
                    {"device": ["zz"], "temp": np.array([1.0], np.float32)})
    finally:
        prod.close()
    rows = _view_rows(
        stub, "stv",
        lambda rs: sum(r["c"] for r in rs if "c" in r) >= 8 * 256)
    assert sum(r["c"] for r in rows
               if r.get("winStart") < BASE + 60_000) == 8 * 256


def test_bad_frame_refused_no_partial_ingest(server_stub):
    """A request mixing a good and a bad frame is refused atomically:
    INVALID_ARGUMENT and NOT ONE row of the good frame lands."""
    stub, ctx = server_stub
    _mk_view(stub, ctx, "badv", "badfr")
    (ts, cols), = _mk_batches(1, 64, seed=7)
    good = encode_batch(ts, cols)
    bad = good[:-3]  # torn
    with pytest.raises(grpc.RpcError) as ei:
        stub.AppendColumnar(pb.AppendColumnarRequest(
            stream_name="badfr", blocks=[good, bad]))
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # non-frame garbage and a forged inner header refuse the same way
    for junk in (b"junk", colframe.encode_frame(b"not columnar")):
        with pytest.raises(grpc.RpcError) as ei:
            stub.AppendColumnar(pb.AppendColumnarRequest(
                stream_name="badfr", blocks=[junk]))
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # the stream is untouched: nothing was appended
    logid = ctx.streams.get_logid("badfr")
    from hstream_tpu.store.api import LSN_INVALID

    assert ctx.store.tail_lsn(logid) == LSN_INVALID
    # and a correct append afterwards still works
    r = stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="badfr", blocks=[good]))
    assert r.rows == 64


def test_framed_nulls_reach_engine_like_absent_fields(server_stub):
    """Null-masked cells on the framed path behave exactly like fields
    a per-record producer never sent: WHERE temp > 0 sees them NULL."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="nulsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW nulv AS SELECT device, COUNT(*) AS c "
                  "FROM nulsrc WHERE temp > 0 "
                  "GROUP BY device, TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-nulv")
    n = 40
    ts = BASE + np.arange(n, dtype=np.int64)
    cols = {"device": ["d0"] * n,
            "temp": np.ones(n, np.float32)}
    nulls = {"temp": (np.arange(n) % 4 == 0)}  # 10 masked out
    stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="nulsrc", blocks=[encode_batch(ts, cols, nulls)]))
    stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="nulsrc",
        blocks=[encode_batch(np.array([BASE + 60_000], np.int64),
                             {"device": ["zz"],
                              "temp": np.array([1.0], np.float32)})]))
    rows = _view_rows(
        stub, "nulv",
        lambda rs: any(r.get("device") == "d0" and r.get("c") == 30
                       for r in rs))
    assert any(r.get("c") == 30 for r in rows), rows


def test_framed_append_admission_and_stats(server_stub):
    """Flow admission gates the framed path (rows+bytes charged), and
    the per-stage append timings land in the stage histograms."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="quotsrc"))
    (ts, cols), = _mk_batches(1, 128, seed=5)
    stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="quotsrc", blocks=[encode_batch(ts, cols)]))
    # stage timings observed (decode/admit/handoff/store)
    for stage in ("append_decode", "append_admit", "append_handoff",
                  "append_store"):
        assert ctx.stats.histogram_percentile(
            "stage_latency_ms", stage, 50) is not None, stage
    assert ctx.stats.stream_stat_get(
        "append_columnar_rows", "quotsrc") == 128
    # 1 rec/s quota, burst 1: the second framed append is refused
    from hstream_tpu.flow import Quota

    ctx.flow.set_quota("stream/quotsrc",
                       Quota(records_per_s=1.0, burst_records=1.0))
    try:
        # debt-based bucket: the first append is admitted (driving the
        # bucket into debt), the next refused with retry-after
        stub.AppendColumnar(pb.AppendColumnarRequest(
            stream_name="quotsrc", blocks=[encode_batch(ts, cols)]))
        with pytest.raises(grpc.RpcError) as ei:
            stub.AppendColumnar(pb.AppendColumnarRequest(
                stream_name="quotsrc", blocks=[encode_batch(ts, cols)]))
        assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    finally:
        ctx.flow.unset_quota("stream/quotsrc")


def test_multi_block_request_is_one_atomic_store_batch(server_stub):
    """All blocks of one request share ONE LSN (like protobuf Append):
    a store failure mid-request can never partially ingest it."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="mbatom"))
    batches = _mk_batches(3, 32, seed=13)
    r = stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="mbatom",
        blocks=[encode_batch(ts, cols) for ts, cols in batches]))
    assert r.rows == 3 * 32
    assert len(r.record_ids) == 3
    assert len({i.batch_id for i in r.record_ids}) == 1
    assert [i.batch_index for i in r.record_ids] == [0, 1, 2]
    logid = ctx.streams.get_logid("mbatom")
    reader = ctx.store.new_reader()
    reader.set_timeout(0)
    reader.start_reading(logid, 0)
    (item,) = reader.read(8)
    assert len(item.payloads) == 3
    assert all(rec.peek_columnar_payload(p) is not None
               for p in item.payloads)


def test_append_front_on_replicated_store_honors_compression():
    """ISSUE 12 review: ReplicatedStore.append_async used to reject the
    compression argument, killing the whole framed path on replicated
    deployments."""
    from hstream_tpu.store.api import Compression
    from hstream_tpu.store.replica import ReplicatedStore

    store = ReplicatedStore(MemLogStore(), [], replication_factor=1)
    try:
        store.create_log(7)
        front = AppendFront(store)
        assert front.stats()["async"] is True
        fut = front.submit(7, [b"abc", b"def"], Compression.ZLIB)
        lsn = fut.result(timeout=10)
        assert lsn > 0
        assert front.stats()["in_flight"] == 0
        front.close()
        reader = store.new_reader()
        reader.set_timeout(0)
        reader.start_reading(7, 0)
        (item,) = reader.read(4)
        assert item.payloads == (b"abc", b"def")
    finally:
        store.close()


def test_gateway_append_columnar_route(server_stub):
    """POST /streams/<name>/appendColumnar proxies the raw frame; a bad
    frame comes back 400 (INVALID_ARGUMENT mapping)."""
    from hstream_tpu.http_gateway import Gateway

    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="gwfr"))
    gw = Gateway(f"127.0.0.1:{ctx.port}")
    try:
        ts = BASE + np.arange(5, dtype=np.int64)
        frame = encode_batch(ts, {"k": ["a"] * 5})
        code, out = gw.handle("POST", "/streams/gwfr/appendColumnar",
                              frame)[:2]
        assert code == 200 and out["rows"] == 5
        assert len(out["record_ids"]) == 1
        code, out = gw.handle("POST", "/streams/gwfr/appendColumnar",
                              frame[:-2])[:2]
        assert code == 400
        code, out = gw.handle("POST", "/streams/gwfr/appendColumnar",
                              None)[:2]
        assert code == 400
    finally:
        gw.close()


def test_framed_rows_visible_to_subscriptions(server_stub):
    """The framed path stores a NORMAL columnar record: existing
    consumers (Fetch) read it unchanged."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="subfr"))
    ts = BASE + np.arange(3, dtype=np.int64)
    stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="subfr",
        blocks=[encode_batch(ts, {"k": ["a", "b", "c"]})]))
    stub.CreateSubscription(pb.Subscription(
        subscription_id="subfr-s", stream_name="subfr"))
    got = stub.Fetch(pb.FetchRequest(subscription_id="subfr-s",
                                     timeout_ms=2000, max_size=4))
    # the subscription wire expands a columnar record per-row (PR 5's
    # _expand_columnar): consumers see ordinary per-row records with
    # the per-row timestamps
    recs = [rec.parse_record(r.record) for r in got.received_records]
    assert [rec.record_to_dict(r)["k"] for r in recs] == ["a", "b", "c"]
    assert [r.header.publish_time_ms for r in recs] == list(ts)
    # a null-masked cell is absent from the delivered row too
    stub.AppendColumnar(pb.AppendColumnarRequest(
        stream_name="subfr",
        blocks=[encode_batch(
            np.array([BASE + 9], np.int64),
            {"k": ["d"], "v": np.array([7.0], np.float32)},
            {"v": np.array([True])})]))
    got = stub.Fetch(pb.FetchRequest(subscription_id="subfr-s",
                                     timeout_ms=2000, max_size=4))
    (only,) = [rec.record_to_dict(rec.parse_record(r.record))
               for r in got.received_records]
    assert only == {"k": "d"}
