"""Scheduler seed (SURVEY §2.3 task distribution): query->server
assignment lives in the CAS-versioned config store; a successor server
adopts queries whose owner's boot epoch predates its own and resumes
them from their snapshots. Two-process test: SIGKILL server A, boot
server B on the same store."""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

import grpc

from hstream_tpu.common import records as rec
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub

from helpers import wait_attached

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE = 1_700_000_000_000


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def wait_up(port, timeout=60.0):
    """Poll Echo with a FRESH channel per attempt until the server
    answers, returning (channel, stub). A channel created while the
    port still refuses connections can wedge in connect-backoff and
    never recover even after the listener appears."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = HStreamApiStub(ch)
        try:
            stub.Echo(pb.EchoRequest(msg="up"), timeout=1)
            return ch, stub
        except grpc.RpcError:
            ch.close()
            time.sleep(0.3)
    raise TimeoutError("server never came up")


def append_rows(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    return stub.Append(req)


def test_successor_adopts_and_resumes_from_snapshot(tmp_path):
    store_dir = str(tmp_path / "store")
    port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "hstream_tpu.server.main",
         "--host", "127.0.0.1", "--port", str(port),
         "--store", store_dir, "--snapshot-interval-ms", "100"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    qid = None
    try:
        ch, stub = wait_up(port)
        stub.CreateStream(pb.Stream(stream_name="src"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE STREAM snk AS SELECT k, COUNT(*) AS c "
                      "FROM src GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
        qs = stub.ListQueries(pb.ListQueriesRequest()).queries
        assert len(qs) == 1
        qid = qs[0].id
        append_rows(stub, "src", [{"k": "a"} for _ in range(10)],
                    [BASE + i for i in range(10)])
        time.sleep(1.5)  # snapshot cadence is 100ms; let state commit
        ch.close()
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(10)

    # successor boots on the same store: it must adopt + resume
    from hstream_tpu.server import scheduler
    from hstream_tpu.server.main import serve

    server, ctx = serve("127.0.0.1", 0, store_dir,
                        snapshot_interval_ms=100)
    try:
        assert qid in ctx.running_queries, "query not adopted"
        a = scheduler.assignment(ctx, qid)
        assert a is not None and a["epoch"] == ctx.boot_epoch
        assert a["node"] == scheduler.node_name(ctx)
        wait_attached(ctx, qid)
        ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
        stub = HStreamApiStub(ch)
        # one more record in the SAME window, then close it: the count
        # must continue from the snapshot (11), not restart at 1
        append_rows(stub, "src", [{"k": "a"}], [BASE + 100])
        append_rows(stub, "src", [{"k": "zz"}], [BASE + 60_000])
        deadline = time.time() + 30
        best = 0
        while time.time() < deadline:
            rows = [rec.record_to_dict(rec.parse_record(r))
                    for r in _read_all(ctx, "snk")]
            counts = [r.get("c", 0) for r in rows
                      if r and r.get("k") == "a"]
            best = max([best] + counts)
            if best >= 11:
                break
            time.sleep(0.3)
        assert best == 11, f"resumed count {best} != 11"
        ch.close()
    finally:
        server.stop(grace=1)
        ctx.shutdown()


def _read_all(ctx, stream):
    from hstream_tpu.common import columnar
    from hstream_tpu.store.api import DataBatch

    logid = ctx.streams.get_logid(stream)
    tail = ctx.store.tail_lsn(logid)
    out = []
    if not tail:
        return out
    r = ctx.store.new_reader()
    r.set_timeout(0)
    r.start_reading(logid, 1, tail)
    while True:
        items = r.read(256)
        if not items:
            break
        for it in items:
            if isinstance(it, DataBatch):
                for p in it.payloads:
                    pr = rec.parse_record(p)
                    rows = columnar.payload_rows(pr.payload)
                    if rows is not None:
                        out.extend(
                            rec.build_record(row).SerializeToString()
                            for row in rows)
                    else:
                        out.append(p)
    return out


def test_adoption_skips_live_owner_epoch(tmp_path):
    """A query whose owner epoch >= ours must NOT be adopted."""
    from hstream_tpu.server import scheduler
    from hstream_tpu.server.context import ServerContext
    from hstream_tpu.store import open_store

    store = open_store("mem://")
    ctx = ServerContext(store)
    scheduler.record_assignment(ctx, "q1")
    # same context tries again: owner epoch == ours -> not adoptable
    assert not scheduler.try_adopt(ctx, "q1")
    # a later-epoch context adopts it
    ctx2 = ServerContext(store, persistence=ctx.persistence)
    assert ctx2.boot_epoch > ctx.boot_epoch
    assert scheduler.try_adopt(ctx2, "q1")
    a = scheduler.assignment(ctx2, "q1")
    assert a["epoch"] == ctx2.boot_epoch
    assert "q1" in scheduler.assignments(ctx2)
