"""Columnar producer path: one RAW record carries a whole column batch
(common/columnar.py); query tasks feed it straight into the lattice
(tasks._run_columnar) — the server-side product fast path."""

import time

import grpc
import numpy as np
import pytest

from hstream_tpu.common import columnar
from hstream_tpu.common import records as rec
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached

BASE = 1_700_000_000_000


def test_codec_roundtrip():
    ts = np.arange(10, dtype=np.int64) + BASE
    cols = {"device": [f"d{i % 3}" for i in range(10)],
            "temp": np.arange(10, dtype=np.float32) * 0.5,
            "n": np.arange(10), "ok": np.arange(10) % 2 == 0}
    blob = columnar.encode_columnar(ts, cols)
    assert columnar.is_columnar(blob)
    ts2, dec = columnar.decode_columnar(blob)
    np.testing.assert_array_equal(ts2, ts)
    kind, arr, d = dec["device"]
    assert kind == "str" and [d[i] for i in arr] == cols["device"]
    np.testing.assert_array_equal(dec["temp"][1], cols["temp"])
    np.testing.assert_array_equal(dec["n"][1], cols["n"])
    np.testing.assert_array_equal(dec["ok"][1], cols["ok"])


@pytest.fixture(scope="module")
def server_stub():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(channel)
    yield stub, ctx
    channel.close()
    server.stop(grace=1)
    ctx.shutdown()


def _append_columnar(stub, stream, ts, cols):
    req = pb.AppendRequest(stream_name=stream)
    req.records.append(rec.build_columnar_record(ts, cols))
    stub.Append(req)


def _view_rows(stub, view, pred, timeout=30):
    rows = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=f"SELECT * FROM {view};"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        if pred(rows):
            break
        time.sleep(0.2)
    return rows


def test_columnar_append_through_view(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="colsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW colview AS SELECT device, COUNT(*) AS c, "
                  "SUM(temp) AS s FROM colsrc WHERE temp > 0 "
                  "GROUP BY device, TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-colview")
    n = 1000
    ts = BASE + np.arange(n, dtype=np.int64) % 5000
    ts.sort()
    devs = [f"d{i % 4}" for i in range(n)]
    temps = np.where(np.arange(n) % 10 == 0, -1.0,
                     1.0).astype(np.float32)  # 100 filtered out
    _append_columnar(stub, "colsrc", ts, {"device": devs, "temp": temps})
    _append_columnar(stub, "colsrc", np.array([BASE + 30_000]),
                     {"device": ["zz"], "temp": np.array([1.0], np.float32)})
    rows = _view_rows(
        stub, "colview",
        lambda rs: len([r for r in rs if r.get("winStart") == BASE]) >= 4)
    closed = {r["device"]: r for r in rows if r.get("winStart") == BASE}
    # per device: 250 records, minus the temp<0 ones (i%10==0 hits d0's
    # residue class i%4==0 in i%20==0... compute exactly instead)
    exp = {f"d{k}": sum(1 for i in range(n)
                        if i % 4 == k and i % 10 != 0)
           for k in range(4)}
    got = {d: r["c"] for d, r in closed.items()}
    assert got == exp, (got, exp)
    for k in range(4):
        assert closed[f"d{k}"]["s"] == pytest.approx(exp[f"d{k}"] * 1.0)


def test_columnar_mixed_with_json_records(server_stub):
    """JSON per-record appends and columnar batches interleave on one
    stream; both feed the same aggregation."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="mixsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW mixview AS SELECT k, COUNT(*) AS c "
                  "FROM mixsrc GROUP BY k, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-mixview")
    req = pb.AppendRequest(stream_name="mixsrc")
    req.records.append(rec.build_record({"k": "a"}, publish_time_ms=BASE))
    stub.Append(req)
    _append_columnar(stub, "mixsrc", np.array([BASE + 1, BASE + 2]),
                     {"k": ["a", "b"]})
    req = pb.AppendRequest(stream_name="mixsrc")
    req.records.append(rec.build_record({"k": "b"},
                                        publish_time_ms=BASE + 3))
    stub.Append(req)
    _append_columnar(stub, "mixsrc", np.array([BASE + 30_000]),
                     {"k": ["zz"]})
    rows = _view_rows(
        stub, "mixview",
        lambda rs: {(r.get("k"), r.get("c")) for r in rs
                    if r.get("winStart") == BASE} >= {("a", 2), ("b", 2)})
    got = {r["k"]: r["c"] for r in rows if r.get("winStart") == BASE}
    assert got.get("a") == 2 and got.get("b") == 2, rows


def test_malformed_columnar_record_is_skipped(server_stub):
    """A forged/corrupt columnar payload must not kill the query task
    (pre-fix: decode raised and the task died CONNECTION_ABORT)."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="badsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW badview AS SELECT k, COUNT(*) AS c "
                  "FROM badsrc GROUP BY k, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-badview")
    req = pb.AppendRequest(stream_name="badsrc")
    req.records.append(rec.build_record(columnar.MAGIC))  # truncated
    req.records.append(rec.build_record(
        columnar.MAGIC + b"\xff\xff\xff\xff garbage"))
    stub.Append(req)
    _append_columnar(stub, "badsrc", np.array([BASE, BASE + 30_000]),
                     {"k": ["a", "zz"]})
    rows = _view_rows(
        stub, "badview",
        lambda rs: any(r.get("k") == "a" and r.get("c") == 1
                       for r in rs if r.get("winStart") == BASE))
    assert any(r.get("k") == "a" and r.get("c") == 1 for r in rows), rows
    task = ctx.running_queries.get("view-badview")
    assert task is not None and task.is_alive()


def test_columnar_records_reach_connector_sink(server_stub, tmp_path):
    """Connector sinks must consume columnar batches too, not silently
    drop them while advancing the checkpoint."""
    import sqlite3

    stub, ctx = server_stub
    db = tmp_path / "colsink.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (a INTEGER, b TEXT)")
    conn.commit()
    conn.close()
    stub.CreateStream(pb.Stream(stream_name="colcsrc"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text=f"CREATE SINK CONNECTOR colsc WITH "
                  f"(type = 'sqlite', stream = 'colcsrc', "
                  f"path = '{db}', table = 't');"))
    _append_columnar(stub, "colcsrc", np.array([BASE, BASE + 1]),
                     {"a": np.array([1, 2]), "b": ["x", "y"]})
    deadline = time.time() + 15
    rows = []
    while time.time() < deadline:
        conn = sqlite3.connect(db)
        rows = conn.execute("SELECT a, b FROM t ORDER BY a").fetchall()
        conn.close()
        if len(rows) == 2:
            break
        time.sleep(0.2)
    assert rows == [(1, "x"), (2, "y")]
    stub.DeleteConnector(pb.DeleteConnectorRequest(id="colsc"))


def test_float_group_key_consistent_across_formats(server_stub):
    """A float GROUP BY value must land in ONE group whether it arrived
    as a JSON python float or a columnar f32 (canon_key)."""
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="fkey"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW fkeyv AS SELECT g, COUNT(*) AS c "
                  "FROM fkey GROUP BY g, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-fkeyv")
    req = pb.AppendRequest(stream_name="fkey")
    req.records.append(rec.build_record({"g": 20.1},
                                        publish_time_ms=BASE))
    stub.Append(req)
    _append_columnar(stub, "fkey", np.array([BASE + 1]),
                     {"g": np.array([20.1], np.float32)})
    _append_columnar(stub, "fkey", np.array([BASE + 30_000]),
                     {"g": np.array([0.0], np.float32)})
    rows = _view_rows(
        stub, "fkeyv",
        lambda rs: any(r.get("c") == 2 for r in rs
                       if r.get("winStart") == BASE))
    closed = [r for r in rows if r.get("winStart") == BASE]
    assert len(closed) == 1 and closed[0]["c"] == 2, rows


def test_columnar_numeric_group_key(server_stub):
    stub, ctx = server_stub
    stub.CreateStream(pb.Stream(stream_name="numcol"))
    stub.ExecuteQuery(pb.CommandQuery(
        stmt_text="CREATE VIEW numcolv AS SELECT sensor, COUNT(*) AS c "
                  "FROM numcol GROUP BY sensor, "
                  "TUMBLING (INTERVAL 10 SECOND) "
                  "GRACE BY INTERVAL 0 SECOND;"))
    wait_attached(ctx, "view-numcolv")
    _append_columnar(stub, "numcol", BASE + np.arange(6, dtype=np.int64),
                     {"sensor": np.array([1, 2, 1, 3, 2, 1])})
    _append_columnar(stub, "numcol", np.array([BASE + 30_000]),
                     {"sensor": np.array([9])})
    rows = _view_rows(
        stub, "numcolv",
        lambda rs: len([r for r in rs if r.get("winStart") == BASE]) >= 3)
    got = {r["sensor"]: r["c"] for r in rows if r.get("winStart") == BASE}
    assert got == {1: 3, 2: 2, 3: 1}, rows
