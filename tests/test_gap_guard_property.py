"""Property tests for the lattice slot-aliasing guard (_gap_guard):
randomized stream-time gaps and hop patterns, checked against a naive
per-record window model (VERDICT item 10)."""

import numpy as np
import pytest

from hstream_tpu.engine import (
    AggKind,
    AggSpec,
    AggregateNode,
    ColumnType,
    HoppingWindow,
    QueryExecutor,
    Schema,
    SourceNode,
    TumblingWindow,
)
from hstream_tpu.engine.expr import Col

BASE = 1_700_000_000_000
SCHEMA = Schema.of(k=ColumnType.STRING)


def make_exec(window):
    node = AggregateNode(
        child=SourceNode("s", SCHEMA), group_keys=[Col("k")],
        window=window, aggs=[AggSpec(AggKind.COUNT_ALL, "c")])
    return QueryExecutor(node, SCHEMA, emit_changes=False,
                         initial_keys=8, batch_capacity=256)


class Model:
    """Naive per-record windowed COUNT with the engine's semantics:
    a record joins every window [start, start+size) with
    start = align(ts) - j*advance; it is dropped late when
    start + size + grace <= the watermark BEFORE its batch; windows
    close (emit) once the watermark passes start + size + grace."""

    def __init__(self, window):
        self.w = window
        self.acc: dict[tuple, int] = {}
        self.wm = -1
        self.closed: dict[tuple, int] = {}

    def feed(self, keys, ts_list):
        w = self.w
        wm_pre = self.wm
        for k, t in zip(keys, ts_list):
            latest = t - t % w.advance_ms
            for j in range(w.windows_per_record):
                start = latest - j * w.advance_ms
                if wm_pre >= 0 and start + w.size_ms + w.grace_ms <= wm_pre:
                    continue  # late
                self.acc[(k, start)] = self.acc.get((k, start), 0) + 1
        self.wm = max(self.wm, max(ts_list))
        for (k, start), c in list(self.acc.items()):
            if start + w.size_ms + w.grace_ms <= self.wm:
                self.closed[(k, start)] = \
                    self.closed.get((k, start), 0) + c
                del self.acc[(k, start)]


def collect(out, closed):
    for r in out:
        key = (r["k"], r["winStart"])
        closed[key] = closed.get(key, 0) + r["c"]


@pytest.mark.parametrize("seed", range(6))
def test_random_gaps_per_record(seed):
    """Single-record batches with random forward jumps — including gaps
    far past the slot horizon (the aliasing case) — and random hops:
    engine closed windows must equal the model exactly."""
    rng = np.random.default_rng(seed)
    if seed % 2 == 0:
        w = TumblingWindow(1000, grace_ms=int(rng.integers(0, 2)) * 500)
    else:
        w = HoppingWindow(3000, 1000,
                          grace_ms=int(rng.integers(0, 2)) * 500)
    ex = make_exec(w)
    model = Model(w)
    closed: dict[tuple, int] = {}
    t = BASE
    for _ in range(60):
        jump = int(rng.choice(
            [17, 333, 1000, 2500,
             w.advance_ms * ex.spec.n_slots + 1234,      # alias the slots
             w.advance_ms * ex.spec.n_slots * 3 + 1]))   # far gap
        t += jump
        k = f"k{int(rng.integers(0, 3))}"
        collect(ex.process([{"k": k}], [t]), closed)
        model.feed([k], [t])
    # final closer drains everything still open
    t += w.advance_ms * ex.spec.n_slots * 4
    collect(ex.process([{"k": "zz"}], [t]), closed)
    model.feed(["zz"], [t])
    closed = {kk: v for kk, v in closed.items() if kk[0] != "zz"}
    expect = {kk: v for kk, v in model.closed.items() if kk[0] != "zz"}
    assert closed == expect, (closed, expect, type(w).__name__)


@pytest.mark.parametrize("seed", range(6, 12))
def test_random_gaps_batched_ordered(seed):
    """Multi-record batches with nondecreasing timestamps and random
    inter-batch jumps (including slot-horizon gaps): engine == model."""
    rng = np.random.default_rng(seed)
    if seed % 2 == 0:
        w = TumblingWindow(2000, grace_ms=0)
    else:
        w = HoppingWindow(4000, 2000, grace_ms=0)
    ex = make_exec(w)
    model = Model(w)
    closed: dict[tuple, int] = {}
    t = BASE
    for _ in range(25):
        jump = int(rng.choice(
            [100, 1900, 4100,
             w.advance_ms * ex.spec.n_slots + 7,
             w.advance_ms * ex.spec.n_slots * 2 + 501]))
        t += jump
        n = int(rng.integers(1, 40))
        offs = np.sort(rng.integers(0, 3 * w.advance_ms, n))
        ts = [t + int(o) for o in offs]
        keys = [f"k{int(rng.integers(0, 4))}" for _ in range(n)]
        rows = [{"k": k} for k in keys]
        collect(ex.process(rows, ts), closed)
        model.feed(keys, ts)
        t = ts[-1]
    t += w.advance_ms * ex.spec.n_slots * 4
    collect(ex.process([{"k": "zz"}], [t]), closed)
    model.feed(["zz"], [t])
    closed = {kk: v for kk, v in closed.items() if kk[0] != "zz"}
    expect = {kk: v for kk, v in model.closed.items() if kk[0] != "zz"}
    assert closed == expect, (closed, expect, type(w).__name__)
