"""Protocol certification (ISSUE 19): the explicit-state model checker
in tools/protocheck drives the REAL scheduler/placer/replica protocol
functions through exhaustive bounded interleavings.

Three properties are pinned here:

* the LIVE tree passes every scenario's invariants (the certification
  itself — a regression in try_adopt_live / _heartbeat_owned /
  Promote/Replicate shows up as a counterexample in this file);
* the mutation gate has teeth: every mechanically reverted PR 9/PR 17
  review fix yields a counterexample (the checker can actually SEE the
  bugs those fixes closed — a green run is evidence, not vacuity);
* counterexample traces are deterministic, serializable schedules:
  replaying one reproduces the same canonical state at every step.
"""

from __future__ import annotations

import json

import pytest

from tools.protocheck.explore import Counterexample, explore, replay
from tools.protocheck.model import DEFAULT_SCENARIOS, SCENARIOS, Model
from tools.protocheck.mutants import BY_NAME, MUTANTS
from tools.protocheck.replica_model import (MiniLogStore, ReplicaModel,
                                            ReplicaScenario,
                                            explore_replica,
                                            replay_replica)

# ---- live-tree certification ----------------------------------------------

# the fast half of the registry runs per-scenario for precise failure
# attribution; the two slowest run together under one budget marker
_FAST = [n for n in DEFAULT_SCENARIOS
         if n in ("skew-2", "mixed-2", "clamp-2", "created-2")]
_SLOW = [n for n in DEFAULT_SCENARIOS if n not in _FAST]


@pytest.mark.parametrize("name", _FAST)
def test_live_tree_certified_fast_scenarios(name):
    res = explore(SCENARIOS[name])
    assert res.ok, (
        f"{name}: live tree violates {res.counterexample.rule}: "
        f"{res.counterexample.message}\n"
        f"trace: {res.counterexample.trace}")
    assert res.states > 5  # the scenario actually explored something


@pytest.mark.parametrize("name", _SLOW)
def test_live_tree_certified_deep_scenarios(name):
    res = explore(SCENARIOS[name])
    assert res.ok, (
        f"{name}: live tree violates {res.counterexample.rule}: "
        f"{res.counterexample.message}\n"
        f"trace: {res.counterexample.trace}")
    assert res.states > 100
    assert res.elapsed_s < 30  # CI bound: whole module stays tier-1


def test_live_tree_replica_model_certified():
    res = explore_replica(ReplicaScenario())
    assert res.ok, (
        f"replica model violates {res.counterexample.rule}: "
        f"{res.counterexample.message}")
    assert res.states > 100


# ---- mutation gate ---------------------------------------------------------


def test_gate_covers_at_least_five_reverted_fixes():
    assert len(MUTANTS) >= 5
    assert len({m.name for m in MUTANTS}) == len(MUTANTS)


@pytest.mark.parametrize("name", sorted(BY_NAME))
def test_mutant_yields_counterexample(name):
    m = BY_NAME[name]
    if m.kind == "replica":
        res = explore_replica(ReplicaScenario(), mutant=m)
    else:
        res = explore(SCENARIOS[m.scenario], mutant=m)
    assert not res.ok, (
        f"mutant {name} (reverts: {m.fix}) went UNNOTICED over "
        f"{res.states} states — the checker lost the invariant that "
        f"certifies this fix")
    ce = res.counterexample
    assert ce.mutant == name
    assert ce.rule and ce.message


def test_mutants_restore_the_live_functions():
    """The patch contextmanagers must leave no residue: after a mutant
    run, the live module attributes are back and a live exploration is
    still clean."""
    import hstream_tpu.server.scheduler as sched

    before = sched.try_adopt_live
    res = explore(SCENARIOS["kill-2"],
                  mutant=BY_NAME["fresh-heartbeat-refusal"])
    assert not res.ok
    assert sched.try_adopt_live is before
    assert explore(SCENARIOS["clamp-2"]).ok


def test_exploration_restores_the_tree_logger_level():
    """quiet_protocol_logs mutes the hstream_tpu root logger during a
    run; the mute must not leak into tests that run after this module
    in the same process (they assert on log records)."""
    import logging

    root = logging.getLogger("hstream_tpu")
    before = root.level
    explore(SCENARIOS["clamp-2"])
    explore_replica(ReplicaScenario())
    assert root.level == before


# ---- counterexample replay determinism ------------------------------------


def test_trace_replays_deterministically():
    m = BY_NAME["fresh-heartbeat-refusal"]
    res = explore(SCENARIOS[m.scenario], mutant=m)
    ce = res.counterexample
    v1, k1, _ = replay(SCENARIOS[m.scenario], ce.trace, mutant=m)
    v2, k2, _ = replay(SCENARIOS[m.scenario], ce.trace, mutant=m)
    assert v1 and v1[0].rule == ce.rule
    assert k1 == k2  # same canonical state at every step
    # the SAME schedule on the LIVE tree is clean: the fix, not the
    # schedule, is what the counterexample demonstrates
    v_live, _, _ = replay(SCENARIOS[m.scenario], ce.trace)
    assert not v_live


def test_stabilized_counterexample_replays_with_convergence():
    m = BY_NAME["legacy-epoch-adopt"]
    res = explore(SCENARIOS[m.scenario], mutant=m)
    ce = res.counterexample
    assert ce.stabilized
    vs, _, _ = replay(SCENARIOS[m.scenario], ce.trace, mutant=m,
                      stabilize=True)
    assert vs and vs[0].rule == ce.rule


def test_replica_trace_replays_deterministically():
    m = BY_NAME["promote-no-epoch-guard"]
    res = explore_replica(ReplicaScenario(), mutant=m)
    ce = res.counterexample
    v1, k1 = replay_replica(ce.trace, mutant=m,
                            stabilize=ce.stabilized)
    v2, k2 = replay_replica(ce.trace, mutant=m,
                            stabilize=ce.stabilized)
    assert v1 and v1[0].rule == ce.rule
    assert k1 == k2
    v_live, _ = replay_replica(ce.trace, stabilize=ce.stabilized)
    assert not v_live


def test_counterexample_json_round_trip():
    m = BY_NAME["lease-unclamped"]
    ce = explore(SCENARIOS[m.scenario], mutant=m).counterexample
    back = Counterexample.from_json(json.loads(json.dumps(ce.to_json())))
    assert back.trace == ce.trace
    assert (back.rule, back.scenario, back.mutant) == \
        (ce.rule, ce.scenario, ce.mutant)
    vs, _, _ = replay(SCENARIOS[back.scenario], back.trace, mutant=m,
                      stabilize=back.stabilized)
    assert vs and vs[0].rule == back.rule


def test_timeline_renders_every_step():
    m = BY_NAME["fresh-heartbeat-refusal"]
    ce = explore(SCENARIOS[m.scenario], mutant=m).counterexample
    _vs, keys, steps = replay(SCENARIOS[m.scenario], ce.trace,
                              mutant=m, timeline=True)
    assert len(steps) == len(ce.trace) + 1  # initial + one per action
    assert steps[0]["action"] == "initial"
    for st in steps:
        assert {"action", "clock_ms", "nodes", "records"} <= set(st)
        for n in st["nodes"]:
            assert {"name", "alive", "epoch", "running"} <= set(n)
    assert len(keys) == len(steps)


# ---- model soundness spot-checks ------------------------------------------


def test_snapshot_restore_is_exact():
    model = Model(SCENARIOS["kill-2"])
    with model.engaged():
        k0 = model.state_key()
        snap = model.snapshot()
        for a in (("advance",), ("crash", 0), ("adopt", 1)):
            pre = model.sched_records()
            model.execute(a)
            model.update_truth(a, pre, model.sched_records())
        assert model.state_key() != k0
        model.restore(snap)
        assert model.state_key() == k0


def test_state_key_is_translation_invariant():
    """Canonicalization folds absolute time out: advancing the clock
    with all heartbeats refreshed in lockstep reaches an
    already-visited canonical state (this is what makes the bounded
    space finite and the visited-set effective)."""
    model = Model(SCENARIOS["pause-2"])
    with model.engaged():
        def hb_all():
            for i in (0, 1):
                a = ("hb", i)
                pre = model.sched_records()
                model.execute(a)
                model.update_truth(a, pre, model.sched_records())
        hb_all()
        k1 = model.state_key()
        pre = model.sched_records()
        model.execute(("advance",))
        model.update_truth(("advance",), pre, model.sched_records())
        hb_all()
        k2 = model.state_key()
        # keys differ only in the advance budget, not in time itself
        strip = [i for i, (a, b) in enumerate(zip(k1, k2)) if a != b]
        assert len(strip) == 1


def test_minilogstore_matches_contract():
    s = MiniLogStore()
    assert not s.log_exists(7)
    s.create_log(7)
    assert s.tail_lsn(7) == 0
    assert s.append(7, b"x") == 1
    s.meta_put("k", b"v")
    assert s.meta_get("k") == b"v"
    snap = s.snapshot()
    s.append(7, b"y")
    s.meta_delete("k")
    s.restore(snap)
    assert s.tail_lsn(7) == 1 and s.meta_get("k") == b"v"


def test_replica_model_runs_real_follower_service():
    from hstream_tpu.store.replica import FollowerService

    model = ReplicaModel(ReplicaScenario())
    assert all(isinstance(f, FollowerService) for f in model.followers)
    assert not model.execute(("promote", 0))
    assert model.followers[0].is_leader
    assert model.followers[0].epoch == 1
    # the duel: r2 promoted at the SAME epoch, then full contact
    # resolves to the higher node id
    assert not model.execute(("promote-dup", 1))
    assert not model.stabilize()
    leaders = [f.node_id for f in model.followers if f.is_leader]
    assert leaders == ["r2"]
