"""Device-resident interval join: equivalence against the retained
host reference path (_FlatIntervalStore batch probing), dispatch/fetch
contracts (join_stats), epoch rebase, store growth, match-buffer
overflow redo, columnar changelog decode, and the key-sharded mirror
(skip-guarded where jax.shard_map is absent, like test_close_batched).

The host path IS the reference: every scenario runs twice — once with
`use_device_join=False` (host), once on the device path — and the
FINAL change per (key, window) must agree exactly (coalescing/deferred
drains only change emission cadence, never final values)."""

import numpy as np
import pytest

from hstream_tpu.engine.join import JoinExecutor
from hstream_tpu.sql import stream_codegen
from hstream_tpu.sql.codegen import make_executor

BASE = 1_700_000_000_000

SQL = ("SELECT l.k, COUNT(*) AS c, SUM(l.x) AS s FROM l INNER JOIN r "
       "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k "
       "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
       "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")


def make_join(sql=SQL, **tune):
    ex = make_executor(stream_codegen(sql),
                       sample_rows=[{"k": "k0", "x": 1.0}])
    assert isinstance(ex, JoinExecutor)
    for k, v in tune.items():
        setattr(ex, k, v)
    return ex


def gen_batches(seed=11, n_batches=12, n=256, n_keys=50, stride=500,
                jitter=500, shuffle=False):
    rng = np.random.default_rng(seed)
    batches = []
    for b in range(n_batches):
        rows = [{"k": f"k{int(i)}", "x": float(v)}
                for i, v in zip(rng.integers(0, n_keys, n),
                                rng.normal(1, 1, n))]
        ts = (BASE + b * stride
              + rng.integers(0, jitter, n).astype(np.int64))
        if shuffle:
            rng.shuffle(ts)
        batches.append((rows, ts.tolist(), "l" if b % 2 else "r"))
    return batches


def run_batches(ex, batches):
    out = []
    for rows, ts, side in batches:
        out.extend(ex.process(rows, ts, stream=side))
    out.extend(ex.flush_changes())
    assert not ex.has_pending_changes()
    return out


def final_changes(rows):
    """Changelog mode: the LAST change per (key, window) is the value."""
    last = {}
    for r in rows:
        last[(r["l.k"], r["winStart"])] = (r["c"], round(r["s"], 3))
    return last


def assert_equivalent(batches, **device_tune):
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))
    dev = make_join(**device_tune)
    dref = final_changes(run_batches(dev, batches))
    assert dev._dev is not None, "device path did not activate"
    assert href == dref
    return host, dev


# ---- equivalence -----------------------------------------------------------


def test_device_join_equivalence_basic():
    _, dev = assert_equivalent(gen_batches())
    assert dev.join_stats["probe_batches"] > 0


def test_device_join_out_of_order_arrivals():
    # unsorted timestamps within each batch, including cross-batch
    # overlap: the probe must see identical store states either way
    _, dev = assert_equivalent(gen_batches(seed=7, jitter=1500,
                                           shuffle=True))
    assert dev.join_stats["probe_dispatches"] == \
        dev.join_stats["probe_batches"]


def test_device_join_watermark_eviction():
    # long stream under capacity pressure: retention (within + grace =
    # 1s) far behind the watermark forces two-sided evictions; late
    # records near the cutoff must match exactly what the pruned host
    # stores produce (the probe's retention mask)
    batches = gen_batches(seed=3, n_batches=30, stride=700, jitter=900)
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))
    dev = make_join()
    dev.DEVICE_STORE_CAPACITY = 1 << 9
    assert final_changes(run_batches(dev, batches)) == href
    assert dev._dev is not None
    assert dev.join_stats["evict_dispatches"] > 0
    counts = dev.device_store_counts()
    # eviction keeps the stores near the live window, not the stream
    assert counts["l"] + counts["r"] < 30 * 256


def test_device_join_key_growth_and_remap():
    # more distinct keys than the inner executor's initial capacity:
    # the code->kid LUT grows and inner grow_keys reshapes mid-run
    batches = gen_batches(seed=5, n_batches=16, n_keys=3000, n=512)
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))
    dev = make_join()
    dref = final_changes(run_batches(dev, batches))
    assert dev._dev is not None
    assert href == dref
    assert dev._inner.spec.n_keys > 1024  # actually grew


def test_device_join_deferred_and_coalesced():
    assert_equivalent(gen_batches(seed=13), match_drain_depth=4,
                      coalesce_rows=2048, defer_change_decode=True,
                      change_drain_depth=3, async_change_drain=True)


def test_device_join_columnar_input():
    batches = gen_batches(seed=17)
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))
    dev = make_join()
    out = []
    for rows, ts, side in batches:
        kk = np.asarray([r["k"] for r in rows], object)
        xx = np.asarray([r["x"] for r in rows], np.float64)
        out.extend(dev.process_columnar(
            np.asarray(ts, np.int64), {"k": kk, "x": xx}, stream=side))
    out.extend(dev.flush_changes())
    assert dev._dev is not None
    assert final_changes(out) == href


def test_device_join_columnar_null_keys_dropped():
    # null-masked key cells drop the record, like a row missing the key
    dev = make_join()
    host = make_join(use_device_join=False)
    for ex in (dev, host):
        # activate via a plain matched pair first
        ex.process([{"k": "a", "x": 1.0}], [BASE], stream="r")
        ex.process([{"k": "a", "x": 2.0}], [BASE + 10], stream="l")
    kk = np.asarray(["a", "a", "a"], object)
    xx = np.asarray([5.0, 7.0, 9.0], np.float64)
    nm = np.asarray([False, True, False])
    out_d = list(dev.process_columnar(
        np.asarray([BASE + 20] * 3, np.int64), {"k": kk, "x": xx},
        {"k": nm}, stream="l"))
    out_d.extend(dev.flush_changes())
    rows = [{"k": "a", "x": 5.0}, {"x": 7.0}, {"k": "a", "x": 9.0}]
    out_h = list(host.process(rows, [BASE + 20] * 3, stream="l"))
    out_h.extend(host.flush_changes())
    assert final_changes(out_d) == final_changes(out_h)


# ---- contracts -------------------------------------------------------------


def test_join_stats_one_dispatch_per_batch():
    dev = make_join(match_drain_depth=8)
    run_batches(dev, gen_batches(seed=19, n_batches=16))
    js = dev.join_stats
    assert js["probe_batches"] > 4
    # THE contract: one fused probe+insert dispatch per micro-batch
    assert js["probe_dispatches"] == js["probe_batches"]
    assert js["match_redispatches"] == 0
    # the aggregate fuses into the probe kernel: matches never leave
    # the device, so the per-batch fetch count is ZERO
    assert js["fused_batches"] == js["probe_batches"]
    assert js["probe_fetches"] == 0


def test_join_stats_fetch_path_stacks_buffers():
    # with fusion disabled (stateless-style fallback), deferred drains
    # stack match buffers: strictly fewer fetches than batches
    dev = make_join(match_drain_depth=8)
    batches = gen_batches(seed=43, n_batches=16)
    for rows, ts, side in batches[:3]:
        dev.process(rows, ts, stream=side)
    assert dev._dev is not None
    dev._dev["feed"] = None  # force the match-fetch path
    for rows, ts, side in batches[3:]:
        dev.process(rows, ts, stream=side)
    dev.flush_changes()
    js = dev.join_stats
    assert js["probe_dispatches"] == js["probe_batches"]
    assert 0 < js["probe_fetches"] < js["probe_batches"]


def test_device_join_match_width_self_sizing():
    # one hot key, both sides dense: per-batch match totals exceed the
    # forced-tiny match width, but the host shadow sizes the buffer
    # EXACTLY before every dispatch — no overflow, no redo, exact
    # values
    def hot(n_batches=5, n=120):
        out = []
        for b in range(n_batches):
            rows = [{"k": "hot", "x": 1.0} for _ in range(n)]
            ts = [BASE + b * 200 + i for i in range(n)]
            out.append((rows, ts, "l" if b % 2 else "r"))
        return out

    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, hot()))
    dev = make_join()
    dev.DEVICE_STORE_CAPACITY = 1 << 10
    batches = hot()
    out = []
    for rows, ts, side in batches[:3]:  # activate the device path
        out.extend(dev.process(rows, ts, stream=side))
    assert dev._dev is not None
    dev._dev["match_cap"] = 64  # shadow must grow it back, exactly
    for rows, ts, side in batches[3:]:
        out.extend(dev.process(rows, ts, stream=side))
    out.extend(dev.flush_changes())
    assert dev.join_stats["match_redispatches"] == 0
    assert dev._dev["match_cap"] >= 120  # self-sized past the force
    assert final_changes(out) == href


def test_probe_kernel_reports_match_overflow():
    # kernel-level overflow contract: a too-narrow match buffer
    # reports the TRUE total in its header, and the probe-only redo at
    # a wider width (same store — the fused kernel never mutates the
    # probed side) recovers every match
    from hstream_tpu.engine import lattice as L

    cap, bcap = 64, 16
    store = L.init_join_store(cap, 0)
    empty = L.init_join_store(cap, 0)
    kern = L.join_probe_insert(cap, bcap, 8, 0, 0)
    batch = np.zeros((4, bcap), np.int32)
    batch[0, :10] = 0
    batch[0, 10:] = L.JOIN_SENT_CODE
    batch[1, :10] = np.arange(10)
    store2, _ = kern(store, empty, batch, np.int32(10), np.int32(5),
                     np.int32(-1000))
    # probe the now-populated store with the same batch: 10 records x
    # ~10 in-window entries >> match_cap 8
    _, pk = kern(empty, store2, batch, np.int32(10), np.int32(100),
                 np.int32(-1000))
    total = int(np.asarray(pk)[0, 0])
    assert total == 100 and total > 8
    wide = L.join_probe_only(cap, bcap, 128, 0, 0)
    pk2 = np.asarray(wide(store2, batch, np.int32(10), np.int32(100),
                          np.int32(-1000)))
    t2, kid, jts, mf, of, mc, oc = L.unpack_join_matches(pk2, 0)
    assert t2 == 100 and len(kid) == 100


def test_device_join_store_grow():
    dev = make_join()
    dev.DEVICE_STORE_CAPACITY = 256
    batches = gen_batches(seed=23, n_batches=10, n=512, stride=100)
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))
    assert final_changes(run_batches(dev, batches)) == href
    assert dev.join_stats["store_grows"] >= 1
    assert dev._dev["cap"] > 256


def test_device_join_epoch_rebase_boundary():
    """The device ring buffers REBASE on the shared epoch instead of
    aborting like the host flat store's 2^41 span guard: crossing the
    (artificially lowered) relative-time threshold mid-stream must
    dispatch a rebase and keep results exact across the boundary."""
    batches = gen_batches(seed=29, n_batches=60, stride=400,
                          jitter=600)  # spans 24s of stream time
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))
    dev = make_join()
    dev.REBASE_REL_MS = 1 << 14  # 16s: at least one rebase mid-run
    assert final_changes(run_batches(dev, batches)) == href
    assert dev._dev is not None
    assert dev.join_stats["rebase_dispatches"] >= 1
    # t0 moved forward past the original anchor
    assert dev._dev["t0"] > int(batches[0][1][0]) - dev.retention_ms


def test_device_join_rebase_down_for_late_batch():
    # a batch older than the join epoch rebases t0 DOWN (negative
    # delta) instead of corrupting relative time
    dev = make_join()
    host = make_join(use_device_join=False)
    warm = gen_batches(seed=31, n_batches=4)
    out_d = list(run_batches(dev, warm))
    out_h = list(run_batches(host, warm))
    assert dev._dev is not None
    t0_before = dev._dev["t0"]
    late_rows = [{"k": "k1", "x": 4.0}]
    late_ts = [t0_before - 5000]
    out_d.extend(dev.process(late_rows, late_ts, stream="l"))
    out_d.extend(dev.flush_changes())
    out_h.extend(host.process(late_rows, late_ts, stream="l"))
    out_h.extend(host.flush_changes())
    assert dev._dev["t0"] < t0_before
    assert final_changes(out_d) == final_changes(out_h)


def test_device_join_snapshot_roundtrip():
    from hstream_tpu.engine.snapshot import (restore_executor,
                                             snapshot_executor)

    batches = gen_batches(seed=37, n_batches=12)
    host = make_join(use_device_join=False)
    href = final_changes(run_batches(host, batches))

    plan = stream_codegen(SQL)
    dev = make_executor(plan, sample_rows=[{"k": "k0", "x": 1.0}])
    out = []
    for rows, ts, side in batches[:6]:
        out.extend(dev.process(rows, ts, stream=side))
    out.extend(dev.flush_changes())
    assert dev._dev is not None  # snapshot taken in DEVICE mode
    blob = snapshot_executor(dev)
    resumed, _ = restore_executor(plan, blob)
    for rows, ts, side in batches[6:]:
        out.extend(resumed.process(rows, ts, stream=side))
    out.extend(resumed.flush_changes())
    assert resumed._dev is not None  # device path re-activated
    assert final_changes(out) == href


def test_host_store_view_matches_reference_store():
    batches = gen_batches(seed=41, n_batches=6)
    host = make_join(use_device_join=False)
    run_batches(host, batches)
    dev = make_join()
    run_batches(dev, batches)
    hv = dev._host_store_view()
    for side in ("l", "r"):
        ref, got = host._stores[side], hv[side]
        assert len(ref) == len(got)
        ref_keys = {k: tss for k, (tss, _r) in ref.by_key.items()}
        got_keys = {k: tss for k, (tss, _r) in got.by_key.items()}
        assert ref_keys == got_keys


# ---- columnar changelog decode ---------------------------------------------


def _changelog_executor():
    from hstream_tpu.engine import (AggKind, AggSpec, AggregateNode,
                                    ColumnType, QueryExecutor, Schema,
                                    SourceNode, TumblingWindow)
    from hstream_tpu.engine.expr import BinOp, Col, Lit

    schema = Schema.of(device=ColumnType.STRING,
                       temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
              AggSpec(AggKind.SUM, "s", input=Col("temp")),
              AggSpec(AggKind.TOPK, "t2", input=Col("temp"), k=2)],
        having=BinOp(">", Col("c"), Lit(1)),
        post_projections=[("device", Col("device")),
                          ("c", Col("c")),
                          ("s2", BinOp("*", Col("s"), Lit(2)))])
    ex = QueryExecutor(node, schema, emit_changes=True,
                       initial_keys=256, batch_capacity=4096)
    ex.defer_change_decode = True
    for k in range(100):
        ex.key_id_for((f"d{k}",))
    return ex


def test_columnar_changelog_decode_matches_perrow_reference():
    ex = _changelog_executor()
    rng = np.random.default_rng(2)
    kids = rng.integers(0, 100, 2048).astype(np.int32)
    temps = rng.normal(20, 5, 2048).astype(np.float32)
    ts = BASE + np.arange(2048, dtype=np.int64) % 500
    ex.process_columnar(kids, ts, {"temp": temps})
    epoch, buf = ex._pending_changes[0]
    pk = np.asarray(buf)
    cols = list(ex._decode_changes(pk, epoch))
    rows = ex._decode_changes_rows(pk, epoch)
    assert len(cols) == len(rows) > 0
    for ra, rb in zip(cols, rows):
        assert set(ra) == set(rb)
        for k in rb:
            va, vb = ra[k], rb[k]
            if isinstance(vb, float):
                assert va == pytest.approx(vb)
            elif isinstance(vb, list):
                assert va == pytest.approx(vb)
            else:
                assert va == vb


def test_changelog_drain_stays_columnar():
    from hstream_tpu.common.columnar import ColumnarEmit

    ex = _changelog_executor()
    ex.defer_change_decode = False
    rng = np.random.default_rng(4)
    kids = rng.integers(0, 100, 1024).astype(np.int32)
    temps = rng.normal(20, 5, 1024).astype(np.float32)
    ts = BASE + np.arange(1024, dtype=np.int64) % 500
    out = ex.process_columnar(kids, ts, {"temp": temps})
    # a lone change batch reaches the caller as ONE columnar batch
    assert isinstance(out, ColumnarEmit)
    assert len(out) > 0
    # and its wire encoding round-trips straight from the columns
    payload = out.to_payload(123)
    assert payload is not None


def test_changelog_decode_no_rows_on_empty():
    ex = _changelog_executor()
    pk = np.zeros((3 + 4, 64), np.int32)  # header n = 0
    assert list(ex._decode_changes(pk, BASE)) == []


# ---- eval_host_vec widening ------------------------------------------------


def test_eval_host_vec_string_and_ifnull_ops():
    from hstream_tpu.engine.expr import (BinOp, Col, Lit, UnOp,
                                         eval_host, eval_host_vec)

    cols = {
        "name": np.asarray(["Ada", " bob ", "Eve", None], object),
        "tags": np.asarray([["a", "b"], ["c"], [], ["d", "e"]],
                           object),
        "x": np.asarray([1.5, -2.0, 0.0, 3.0]),
    }
    # reference rows carry plain Python scalars, like decoded records
    rows = [{"name": cols["name"][i], "tags": cols["tags"][i],
             "x": float(cols["x"][i])} for i in range(4)]

    exprs = [
        UnOp("TO_UPPER", BinOp("IFNULL", Col("name"), Lit("?"))),
        UnOp("TRIM", BinOp("IFNULL", Col("name"), Lit(""))),
        UnOp("STRLEN", BinOp("IFNULL", Col("name"), Lit(""))),
        UnOp("ARR_LENGTH", Col("tags")),
        BinOp("ARR_CONTAINS", Col("tags"), Lit("a")),
        BinOp("ARR_JOIN", Col("tags"), Lit("-")),
        UnOp("IS_STR", BinOp("IFNULL", Col("name"), Lit(0))),
        UnOp("SIGN", Col("x")),
    ]
    for e in exprs:
        vec = eval_host_vec(e, cols)
        ref = [eval_host(e, r) for r in rows]
        assert list(np.asarray(vec)) == ref, e


def test_join_projection_stays_columnar():
    """A joined HAVING + string projection decodes through the
    columnar pass (no per-row fallback): the emitted batch is a
    ColumnarEmit."""
    from hstream_tpu.common.columnar import ColumnarEmit

    sql = ("SELECT TO_UPPER(l.k) AS kk, COUNT(*) AS c "
           "FROM l INNER JOIN r WITHIN (INTERVAL 1 SECOND) "
           "ON l.k = r.k GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    ex = make_join(sql)
    ex.process([{"k": "a"}, {"k": "b"}], [BASE, BASE + 1], stream="r")
    out = ex.process([{"k": "a"}, {"k": "b"}], [BASE + 10, BASE + 11],
                     stream="l")
    out = list(out) + list(ex.flush_changes())
    assert any(r.get("kk") in ("A", "B") for r in out)
    # the inner drain produced a columnar batch at least once
    ex2 = make_join(sql)
    ex2.process([{"k": "a"}], [BASE], stream="r")
    inner_out = ex2.process([{"k": "a"}], [BASE + 5], stream="l")
    assert isinstance(inner_out, (list, ColumnarEmit))


# ---- sharded mirror --------------------------------------------------------


def _has_shard_map() -> bool:
    # the parallel package shims jax.shard_map across jax versions
    # (jax.experimental.shard_map on older builds), so the gate only
    # needs the shim to import — not a top-level jax.shard_map
    try:
        from hstream_tpu.parallel.lattice import shard_map  # noqa: F401
    except Exception:  # noqa: BLE001 — no usable shard_map transform
        return False
    return True


@pytest.mark.skipif(not _has_shard_map(),
                    reason="jax.shard_map unavailable in this jax")
def test_sharded_join_kernels_match_single_chip():
    """Key-sharded probe/insert/evict vs the single-chip kernels: same
    batches, same matches (order within the concat may differ by
    shard, so compare as multisets) and same surviving entries."""
    import jax
    from jax.sharding import Mesh

    from hstream_tpu.engine import lattice as L
    from hstream_tpu.parallel.lattice import ShardedJoinLattice

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = Mesh(np.array(devs[:2]), ("key",))
    cap, bcap, mcap = 64, 16, 64
    sj = ShardedJoinLattice(mesh, "key", cap, bcap, mcap, 1, 1)
    sl = sj.init_store("l")
    sr = sj.init_store("r")
    ref_l = L.init_join_store(cap, 1)
    ref_r = L.init_join_store(cap, 1)
    kern = L.join_probe_insert(cap, bcap, mcap, 1, 1)
    rng = np.random.default_rng(8)
    within = np.int32(100)
    cutoff = np.int32(-(1 << 31))
    ref_matches, sh_matches = [], []
    for b in range(6):
        n = 12
        batch = np.zeros((5, bcap), np.int32)
        codes = np.sort(rng.integers(0, 6, n)).astype(np.int32)
        ts = (b * 50 + np.arange(n)).astype(np.int32)
        order = np.lexsort((ts, codes))
        batch[0, :n] = codes[order]
        batch[0, n:] = L.JOIN_SENT_CODE
        batch[1, :n] = ts[order]
        batch[2, :n] = codes[order] + 100          # kid
        batch[4, :n] = rng.integers(0, 99, n)      # one payload col
        side = "l" if b % 2 else "r"
        if side == "l":
            ref_l, pk = kern(ref_l, ref_r, batch, np.int32(n), within,
                             cutoff)
            sl, spk = sj.probe_insert("l", sl, sr, batch, np.int32(n),
                                      within, cutoff)
        else:
            ref_r, pk = kern(ref_r, ref_l, batch, np.int32(n), within,
                             cutoff)
            sr, spk = sj.probe_insert("r", sr, sl, batch, np.int32(n),
                                      within, cutoff)
        t, kid, jts, mf, of, mc, oc = L.unpack_join_matches(
            np.asarray(pk), 1)
        ref_matches += list(zip(kid.tolist(), jts.tolist(),
                                mc[0].tolist(), oc[0].tolist()))
        st, skid, sjts, smf, sof, smc, soc = sj.unpack_matches(
            np.asarray(spk), side)
        assert st == t
        sh_matches += list(zip(skid.tolist(), sjts.tolist(),
                               smc[0].tolist(), soc[0].tolist()))
    assert sorted(ref_matches) == sorted(sh_matches)
    # two-sided eviction parity
    ev = L.join_evict(cap, 1, 1)
    rl, rr, nref = ev(ref_l, ref_r, np.int32(120), np.int32(0))
    sl2, sr2, nsh = sj.evict(sl, sr, np.int32(120), np.int32(0))
    assert int(np.asarray(nref).sum()) == int(np.asarray(nsh).sum())
    got = np.asarray(sl2["code"])
    ref = np.asarray(rl["code"])
    live_ref = sorted(ref[ref < L.JOIN_SENT_CODE].tolist())
    live_got = sorted(got[got < L.JOIN_SENT_CODE].flatten().tolist())
    assert live_ref == live_got
