"""Stream-stream interval JOIN tests (reference Stream.hs:222-300 and
the SQL join path of Codegen.hs:253-266; BASELINE config 5 shape)."""

import pytest

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.join import JoinExecutor
from hstream_tpu.sql import stream_codegen
from hstream_tpu.sql.codegen import make_executor

BASE = 1_700_000_000_000


def make_join_executor(sql, sample):
    plan = stream_codegen(sql)
    ex = make_executor(plan, sample_rows=sample)
    assert isinstance(ex, JoinExecutor)
    return ex


def test_join_stateless_pairs():
    ex = make_join_executor(
        "SELECT s1.x, s2.y FROM s1 INNER JOIN s2 "
        "WITHIN (INTERVAL 10 SECOND) ON s1.k = s2.k EMIT CHANGES;",
        [{"k": "a", "x": 1.0}])
    out = ex.process([{"k": "a", "x": 1.0}], [BASE], stream="s1")
    assert out == []  # nothing on the other side yet
    out = ex.process([{"k": "a", "y": 2.0}], [BASE + 1000], stream="s2")
    assert len(out) == 1
    assert out[0]["s1.x"] == 1.0 and out[0]["s2.y"] == 2.0
    # outside WITHIN: no match
    out = ex.process([{"k": "a", "y": 9.0}], [BASE + 60_000], stream="s2")
    assert out == []
    # wrong key: no match
    out = ex.process([{"k": "b", "x": 5.0}], [BASE + 61_000], stream="s1")
    assert out == []


def test_join_is_symmetric_and_matches_multiple():
    ex = make_join_executor(
        "SELECT s1.x, s2.y FROM s1 INNER JOIN s2 "
        "WITHIN (INTERVAL 10 SECOND) ON s1.k = s2.k EMIT CHANGES;",
        [{"k": "a", "x": 0.0}])
    ex.process([{"k": "a", "y": 1.0}, {"k": "a", "y": 2.0}],
               [BASE, BASE + 100], stream="s2")
    out = ex.process([{"k": "a", "x": 7.0}], [BASE + 200], stream="s1")
    assert sorted(r["s2.y"] for r in out) == [1.0, 2.0]
    assert all(r["s1.x"] == 7.0 for r in out)


def test_join_groupby_window_aggregate():
    ex = make_join_executor(
        "SELECT s2.loc, SUM(s1.x) AS total FROM s1 INNER JOIN s2 "
        "WITHIN (INTERVAL 10 SECOND) ON s1.k = s2.k "
        "GROUP BY s2.loc, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;",
        [{"k": "a", "x": 1.0}])
    out = []
    out += ex.process([{"k": "a", "loc": "sf"}, {"k": "b", "loc": "la"}],
                      [BASE, BASE + 10], stream="s2")
    out += ex.process([{"k": "a", "x": 1.5}, {"k": "a", "x": 2.5},
                       {"k": "b", "x": 10.0}],
                      [BASE + 100, BASE + 200, BASE + 300], stream="s1")
    out += ex.process([{"k": "a", "loc": "sf"}], [BASE + 40_000],
                      stream="s2")
    out += ex.process([{"k": "a", "x": 0.5}], [BASE + 40_001], stream="s1")
    # changelog mode: the last change per (loc, window) is the final value
    rows = {}
    for r in out:
        if r.get("winStart") == BASE:
            rows[r["s2.loc"]] = r
    assert rows["sf"]["total"] == pytest.approx(4.0)
    assert rows["la"]["total"] == pytest.approx(10.0)


def test_join_deferred_async_changes_match_sync():
    """The change-drain knobs proxy through the join onto its inner
    aggregate (set BEFORE the inner lazily exists): deferred + async +
    coalesced emission must match the synchronous join changelog after
    flush_changes() (ISSUE 1: join change extraction off the hot loop)."""
    import numpy as np

    sql = ("SELECT l.k, COUNT(*) AS c, SUM(l.x) AS s FROM l INNER JOIN r "
           "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k "
           "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    rng = np.random.default_rng(11)
    batches = []
    for b in range(12):
        rows = [{"k": f"k{int(i)}", "x": 1.0}
                for i in rng.integers(0, 50, 256)]
        ts = [BASE + b * 500 + i % 500 for i in range(256)]
        batches.append((rows, ts, "l" if b % 2 else "r"))

    def run(tune: bool):
        ex = make_join_executor(sql, [{"k": "k0", "x": 1.0}])
        if tune:
            # before the inner executor exists — must still apply
            ex.defer_change_decode = True
            ex.change_drain_depth = 3
            ex.async_change_drain = True
            ex.coalesce_rows = 1024
        out = []
        for rows, ts, side in batches:
            out.extend(ex.process(rows, ts, stream=side))
        out.extend(ex.flush_changes())
        assert not ex.has_pending_changes()
        if tune:
            assert ex._inner is not None
            assert ex._inner.defer_change_decode is True
            assert ex._inner.async_change_drain is True
        return out

    sync_rows = run(False)
    tuned_rows = run(True)
    assert len(sync_rows) > 0

    def canon(rows):
        return sorted((r["l.k"], r["winStart"], r["c"], r["s"])
                      for r in rows)

    # coalescing merges micro-batches, so per-batch change cadence
    # differs; the FINAL change per (key, window) must agree
    def final(rows):
        last = {}
        for r in rows:
            last[(r["l.k"], r["winStart"])] = (r["c"], r["s"])
        return last

    assert final(sync_rows) == final(tuned_rows)


def test_join_timestamp_is_max_of_pair():
    # reference: joined record ts = max(ts1, ts2) (Stream.hs:298)
    ex = make_join_executor(
        "SELECT s1.k, COUNT(*) AS c FROM s1 INNER JOIN s2 "
        "WITHIN (INTERVAL 10 SECOND) ON s1.k = s2.k "
        "GROUP BY s1.k, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;",
        [{"k": "a", "x": 1.0}])
    # left at BASE+2s, right at BASE+12s -> joined ts lands in 2nd window
    out = []
    out += ex.process([{"k": "a"}], [BASE + 2_000], stream="s1")
    out += ex.process([{"k": "a"}], [BASE + 12_000], stream="s2")
    assert all(r.get("winStart") != BASE for r in out)
    win2 = [r for r in out if r.get("winStart") == BASE + 10_000]
    assert len(win2) == 1 and win2[0]["c"] == 1


def test_join_rejects_bad_condition():
    # caught at validation now (refine._validate_join), before codegen
    from hstream_tpu.common.errors import SQLError

    with pytest.raises(SQLError):
        plan = stream_codegen(
            "SELECT s1.x FROM s1 INNER JOIN s2 "
            "WITHIN (INTERVAL 10 SECOND) ON s1.k = s1.j EMIT CHANGES;")
        make_executor(plan, sample_rows=[{"k": 1, "j": 1}])


def test_join_alias_qualifiers():
    ex = make_join_executor(
        "SELECT a.x, b.y FROM s1 AS a INNER JOIN s2 AS b "
        "WITHIN (INTERVAL 10 SECOND) ON a.k = b.k EMIT CHANGES;",
        [{"k": "a", "x": 1.0}])
    ex.process([{"k": "z", "x": 3.0}], [BASE], stream="s1")
    out = ex.process([{"k": "z", "y": 4.0}], [BASE + 50], stream="s2")
    assert len(out) == 1
    # select items are named by their SQL text (alias-qualified)
    assert out[0]["a.x"] == 3.0 and out[0]["b.y"] == 4.0


def test_flat_store_rejects_timestamp_span_overflow():
    """A rebase to a much older t0 must fail loudly instead of letting
    existing rows' composite offsets overflow into a neighboring key
    code's range (which silently corrupts probes)."""
    import numpy as np
    import pytest as _pytest

    from hstream_tpu.common.errors import SQLCodegenError
    from hstream_tpu.engine.join import _FlatIntervalStore

    st = _FlatIntervalStore([("a",), ("b",)])
    big = 3_000_000_000_000  # > 2^41
    st.insert_sorted(np.array([0], np.int64), np.array([big], np.int64),
                     np.array([{"x": 1}], object))
    with _pytest.raises(SQLCodegenError):
        st.insert_sorted(np.array([1], np.int64),
                         np.array([0], np.int64),   # bogus epoch-0 ts
                         np.array([{"x": 2}], object))
