"""Engine semantics tests against hand-computed expectations.

Window/grace/late-record semantics follow the reference
(TimeWindowedStream.hs windowsFor / grace drop), checked here on the CPU
backend with tiny shapes.
"""

import numpy as np
import pytest

from hstream_tpu.engine import (
    AggKind,
    AggSpec,
    AggregateNode,
    ColumnType,
    FilterNode,
    QueryExecutor,
    Schema,
    SourceNode,
    TumblingWindow,
    HoppingWindow,
)
from hstream_tpu.engine.expr import BinOp, Col, Lit

SCHEMA = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT,
                   humidity=ColumnType.FLOAT)

BASE = 1_700_000_000_000  # absolute ms


def source():
    return SourceNode(stream="s", schema=SCHEMA)


def make_exec(aggs, window, *, where=None, group=("device",),
              emit_changes=False, having=None, post=None):
    child = source() if where is None else FilterNode(source(), where)
    node = AggregateNode(
        child=child,
        group_keys=[Col(g) for g in group],
        window=window,
        aggs=list(aggs),
        having=having,
        post_projections=post or [],
    )
    return QueryExecutor(node, SCHEMA, emit_changes=emit_changes,
                         initial_keys=8, batch_capacity=256)


def rows_of(*pairs):
    """pairs of (device, temp, ts_offset_ms)"""
    rows = [{"device": d, "temp": t} for d, t, _ in pairs]
    ts = [BASE + off for _, _, off in pairs]
    return rows, ts


COUNT = AggSpec(AggKind.COUNT_ALL, "cnt")
SUM_T = AggSpec(AggKind.SUM, "total", input=Col("temp"))


def by_key(emitted):
    return {(r["device"], r.get("winStart")): r for r in emitted}


def test_tumbling_count_sum_close():
    ex = make_exec([COUNT, SUM_T], TumblingWindow(10_000, grace_ms=0))
    rows, ts = rows_of(("a", 1.0, 0), ("a", 2.0, 1000), ("b", 5.0, 2000),
                       ("a", 3.0, 9999))
    out = ex.process(rows, ts)
    assert out == []  # nothing closed yet
    # a record at +10s closes the first window
    rows2, ts2 = rows_of(("b", 7.0, 10_500))
    out2 = ex.process(rows2, ts2)
    got = by_key(out2)
    assert got[("a", BASE)]["cnt"] == 3
    assert got[("a", BASE)]["total"] == pytest.approx(6.0)
    assert got[("a", BASE)]["winEnd"] == BASE + 10_000
    assert got[("b", BASE)]["cnt"] == 1
    assert got[("b", BASE)]["total"] == pytest.approx(5.0)
    assert len(out2) == 2


def test_tumbling_emit_changes():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0),
                   emit_changes=True)
    rows, ts = rows_of(("a", 1.0, 0), ("a", 1.0, 100))
    out = ex.process(rows, ts)
    # batched changelog: one change per touched (key, window) per batch
    assert len(out) == 1
    assert out[0]["cnt"] == 2 and out[0]["device"] == "a"
    out2 = ex.process(*rows_of(("a", 1.0, 200)))
    assert out2[0]["cnt"] == 3


def test_late_records_dropped():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0))
    ex.process(*rows_of(("a", 1.0, 0)))
    ex.process(*rows_of(("a", 1.0, 25_000)))  # watermark to 25s, closes w0
    # record for window [0,10s) is now late; window [20s,30s) still open
    out = ex.process(*rows_of(("a", 9.9, 5_000), ("a", 1.0, 21_000)))
    assert out == []
    out = ex.process(*rows_of(("a", 1.0, 30_000)))
    got = by_key(out)
    assert got[("a", BASE + 20_000)]["cnt"] == 2  # late record not counted
    assert ("a", BASE) not in got


def test_grace_keeps_late_window_open():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=5_000))
    ex.process(*rows_of(("a", 1.0, 0)))
    ex.process(*rows_of(("a", 1.0, 12_000)))  # within grace for w0
    out = ex.process(*rows_of(("a", 1.0, 5_000)))  # late but in grace
    assert out == []
    out = ex.process(*rows_of(("a", 1.0, 15_100)))  # wm passes 10s+5s grace
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 2


def test_hopping_windows_multi_assign():
    # HOP(size=20s, advance=10s): record at t=15s belongs to [0,20) and [10,30)
    ex = make_exec([COUNT], HoppingWindow(20_000, 10_000, grace_ms=0))
    ex.process(*rows_of(("a", 1.0, 15_000)))
    out = ex.process(*rows_of(("a", 1.0, 45_000)))
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 1
    assert got[("a", BASE + 10_000)]["cnt"] == 1


def test_min_max_avg():
    aggs = [AggSpec(AggKind.MIN, "mn", input=Col("temp")),
            AggSpec(AggKind.MAX, "mx", input=Col("temp")),
            AggSpec(AggKind.AVG, "avg", input=Col("temp"))]
    ex = make_exec(aggs, TumblingWindow(10_000, grace_ms=0))
    ex.process(*rows_of(("a", 3.0, 0), ("a", -1.5, 100), ("a", 7.0, 200)))
    out = ex.process(*rows_of(("a", 0.0, 11_000)))
    r = by_key(out)[("a", BASE)]
    assert r["mn"] == pytest.approx(-1.5)
    assert r["mx"] == pytest.approx(7.0)
    assert r["avg"] == pytest.approx((3.0 - 1.5 + 7.0) / 3)


def test_where_filter_on_device():
    where = BinOp(">", Col("temp"), Lit(0.0))
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0), where=where)
    ex.process(*rows_of(("a", 5.0, 0), ("a", -5.0, 100), ("a", 1.0, 200)))
    out = ex.process(*rows_of(("a", 1.0, 11_000)))
    assert by_key(out)[("a", BASE)]["cnt"] == 2


def test_string_equality_filter():
    where = BinOp("=", Col("device"), Lit("a"))
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0), where=where,
                   group=("device",))
    ex.process(*rows_of(("a", 1.0, 0), ("b", 1.0, 100), ("a", 1.0, 200)))
    out = ex.process(*rows_of(("b", 1.0, 11_000)))
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 2
    assert ("b", BASE) not in got


def test_having_and_projection():
    having = BinOp(">=", Col("cnt"), Lit(2))
    post = [("device", Col("device")), ("doubled", BinOp("*", Col("cnt"), Lit(2)))]
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0),
                   having=having, post=post)
    ex.process(*rows_of(("a", 1.0, 0), ("a", 1.0, 100), ("b", 1.0, 200)))
    out = ex.process(*rows_of(("b", 1.0, 11_000)))
    assert len(out) == 1
    assert out[0]["doubled"] == 4 and out[0]["device"] == "a"


def test_approx_count_distinct():
    aggs = [AggSpec(AggKind.APPROX_COUNT_DISTINCT, "uniq", input=Col("temp"))]
    ex = make_exec(aggs, TumblingWindow(10_000, grace_ms=0))
    n_distinct = 500
    rows = [{"device": "a", "temp": float(i % n_distinct)} for i in range(2000)]
    ts = [BASE + i for i in range(2000)]
    ex.process(rows, ts)
    out = ex.process(*rows_of(("a", 0.0, 11_000)))
    uniq = by_key(out)[("a", BASE)]["uniq"]
    assert abs(uniq - n_distinct) / n_distinct < 0.15


def test_approx_quantile():
    aggs = [AggSpec(AggKind.APPROX_QUANTILE, "p50", input=Col("temp"),
                    quantile=0.5)]
    ex = make_exec(aggs, TumblingWindow(10_000, grace_ms=0))
    rng = np.random.default_rng(1)
    vals = rng.lognormal(2.0, 1.0, size=5000)
    rows = [{"device": "a", "temp": float(v)} for v in vals]
    ts = [BASE + i for i in range(5000)]
    ex.process(rows, ts)
    out = ex.process(*rows_of(("a", 0.0, 11_000)))
    p50 = by_key(out)[("a", BASE)]["p50"]
    true = float(np.quantile(vals, 0.5))
    assert abs(p50 - true) / true < 0.10


def test_global_groupby_no_window():
    ex = make_exec([COUNT, SUM_T], window=None, emit_changes=True)
    out = ex.process(*rows_of(("a", 1.0, 0), ("b", 2.0, 50)))
    got = {r["device"]: r for r in out}
    assert got["a"]["cnt"] == 1 and got["b"]["total"] == pytest.approx(2.0)
    assert "winStart" not in got["a"]
    out2 = ex.process(*rows_of(("a", 3.0, 100)))
    got2 = {r["device"]: r for r in out2}
    assert got2["a"]["cnt"] == 2 and got2["a"]["total"] == pytest.approx(4.0)
    assert "b" not in got2  # untouched keys not re-emitted


def test_key_growth():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0))
    rows = [{"device": f"d{i}", "temp": 1.0} for i in range(50)]  # > 8 keys
    ts = [BASE + i for i in range(50)]
    ex.process(rows, ts)
    out = ex.process(*rows_of(("d0", 1.0, 11_000)))
    assert len(out) == 50
    assert all(r["cnt"] == 1 for r in out)


def test_peek_live_state():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0))
    closed = ex.process(*rows_of(("a", 1.0, 0), ("a", 1.0, 100),
                                 ("b", 1.0, 12_000)))
    # the watermark at +12s closed window [BASE, BASE+10s) during process
    assert by_key(closed)[("a", BASE)]["cnt"] == 2
    # peek shows the still-open window only
    got = by_key(ex.peek())
    assert got[("b", BASE + 10_000)]["cnt"] == 1
    assert ("a", BASE) not in got


def test_count_col_and_avg_skip_nulls():
    aggs = [AggSpec(AggKind.COUNT, "c", input=Col("temp")),
            AggSpec(AggKind.AVG, "avg", input=Col("temp")),
            AggSpec(AggKind.COUNT_ALL, "call")]
    ex = make_exec(aggs, TumblingWindow(10_000, grace_ms=0))
    rows = [{"device": "a", "temp": 2.0}, {"device": "a"},  # temp missing
            {"device": "a", "temp": 4.0}, {"device": "a", "temp": None}]
    ts = [BASE + i for i in range(4)]
    ex.process(rows, ts)
    out = ex.process(*rows_of(("a", 0.0, 11_000)))
    r = by_key(out)[("a", BASE)]
    assert r["call"] == 4          # COUNT(*) counts all rows
    assert r["c"] == 2             # COUNT(temp) skips nulls
    assert r["avg"] == pytest.approx(3.0)  # AVG over non-null only


def test_nan_does_not_poison_min_max():
    aggs = [AggSpec(AggKind.MIN, "mn", input=Col("temp")),
            AggSpec(AggKind.MAX, "mx", input=Col("temp")),
            AggSpec(AggKind.SUM, "s", input=Col("temp"))]
    ex = make_exec(aggs, TumblingWindow(10_000, grace_ms=0))
    ex.process(*rows_of(("a", float("nan"), 0), ("a", 5.0, 100),
                        ("a", float("inf"), 200)))
    out = ex.process(*rows_of(("a", 0.0, 11_000)))
    r = by_key(out)[("a", BASE)]
    assert r["mn"] == 5.0 and r["mx"] == 5.0 and r["s"] == 5.0


def test_hll_int_column_high_values():
    # int inputs >= 2^24 must not collapse via a float32 cast
    schema = Schema.of(device=ColumnType.STRING, uid=ColumnType.INT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.APPROX_COUNT_DISTINCT, "u", input=Col("uid"))])
    ex = QueryExecutor(node, schema, emit_changes=False, initial_keys=8)
    n = 2000
    rows = [{"device": "a", "uid": (1 << 24) + i} for i in range(n)]
    ex.process(rows, [BASE + i for i in range(n)])
    out = ex.process([{"device": "a", "uid": 1}], [BASE + 11_000])
    u = by_key(out)[("a", BASE)]["u"]
    assert abs(u - n) / n < 0.15, u


def test_rebase_preserves_open_windows():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=0))
    ex.rebase_threshold = 40_000  # force a rebase quickly
    ex.process(*rows_of(("a", 1.0, 0)))
    ex.process(*rows_of(("a", 1.0, 50_000)))   # triggers rebase + closes w0
    ex.process(*rows_of(("a", 1.0, 52_000)))   # same open window post-rebase
    out = ex.process(*rows_of(("a", 1.0, 61_000)))
    got = by_key(out)
    assert got[("a", BASE + 50_000)]["cnt"] == 2


def test_gap_split_preserves_in_grace_suffix_records():
    # after a big stream gap, an in-grace out-of-order record in the same
    # batch as the jump must still aggregate (the jump doesn't make it late)
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=60_000))
    ex.process(*rows_of(("a", 1.0, 0)))
    # big jump + an out-of-order record within grace of window [0,10s)
    big = 500_000  # far beyond slot range, forces the split path
    out = ex.process(*rows_of(("b", 1.0, big), ("a", 1.0, 5_000)))
    assert out == []
    out = ex.process(*rows_of(("a", 1.0, big + 80_000)))
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 2  # both t=0 and t=5s records counted


def test_nested_filters_all_applied():
    from hstream_tpu.engine import FilterNode
    inner = FilterNode(source(), BinOp(">", Col("temp"), Lit(0.0)))
    outer = FilterNode(inner, BinOp("<", Col("temp"), Lit(10.0)))
    node = AggregateNode(child=outer, group_keys=[Col("device")],
                         window=TumblingWindow(10_000, grace_ms=0),
                         aggs=[COUNT])
    ex = QueryExecutor(node, SCHEMA, emit_changes=False, initial_keys=8)
    ex.process(*rows_of(("a", -5.0, 0), ("a", 5.0, 100), ("a", 50.0, 200)))
    out = ex.process(*rows_of(("a", 5.0, 11_000)))
    assert by_key(out)[("a", BASE)]["cnt"] == 1


def test_out_of_order_within_grace():
    ex = make_exec([COUNT], TumblingWindow(10_000, grace_ms=20_000))
    ex.process(*rows_of(("a", 1.0, 15_000)))
    ex.process(*rows_of(("a", 1.0, 5_000)))   # out of order, within grace
    ex.process(*rows_of(("a", 1.0, 8_000)))
    out = ex.process(*rows_of(("a", 1.0, 40_100)))
    got = by_key(out)
    assert got[("a", BASE)]["cnt"] == 2
    assert got[("a", BASE + 10_000)]["cnt"] == 1


def test_columnar_fast_path_matches_row_path():
    aggs = [COUNT, SUM_T,
            AggSpec(AggKind.MIN, "mn", input=Col("temp")),
            AggSpec(AggKind.APPROX_COUNT_DISTINCT, "u", input=Col("temp"))]
    win = TumblingWindow(10_000, grace_ms=0)
    ref = make_exec(aggs, win)
    col = make_exec(aggs, win)
    rng = np.random.default_rng(3)
    n = 700
    devs = [f"d{int(i)}" for i in rng.integers(0, 6, size=n)]
    temps = rng.normal(10, 4, size=n).astype(np.float32)
    ts = BASE + np.sort(rng.integers(0, 35_000, size=n)).astype(np.int64)

    rows = [{"device": d, "temp": float(t)} for d, t in zip(devs, temps)]
    out_ref = []
    for i in range(0, n, 250):
        out_ref.extend(ref.process(rows[i:i + 250], ts[i:i + 250].tolist()))

    out_col = []
    for i in range(0, n, 250):
        sl = slice(i, i + 250)
        kids = np.array([col.key_id_for((d,)) for d in devs[sl]],
                        dtype=np.int32)
        enc = np.array([col.dicts["device"].encode(d) for d in devs[sl]],
                       dtype=np.int32)
        out_col.extend(col.process_columnar(
            kids, ts[sl], {"temp": temps[sl], "device": enc}))

    closer_rows = [{"device": "d0", "temp": 0.0}]
    closer_ts = [int(BASE + 90_000)]
    out_ref.extend(ref.process(closer_rows, closer_ts))
    kid = np.array([col.key_id_for(("d0",))], dtype=np.int32)
    out_col.extend(col.process_columnar(
        kid, np.array(closer_ts, dtype=np.int64),
        {"temp": np.zeros(1, np.float32),
         "device": np.array([col.dicts["device"].encode("d0")], np.int32)}))

    k_ref = by_key(out_ref)
    k_col = by_key(out_col)
    assert set(k_ref) == set(k_col)
    for key in k_ref:
        for name in ("cnt", "total", "mn", "u"):
            assert k_col[key][name] == pytest.approx(k_ref[key][name],
                                                     rel=1e-5), (key, name)


def test_columnar_gap_split_matches_row_path():
    win = TumblingWindow(10_000, grace_ms=0)
    ref = make_exec([COUNT], win)
    col = make_exec([COUNT], win)
    # one batch containing a slot-aliasing jump (W*advance = 30s for
    # grace 0): starts 0 and 90_000 share residue 0 mod 30_000
    rows = [{"device": "a", "temp": 1.0}, {"device": "a", "temp": 1.0},
            {"device": "a", "temp": 1.0}]
    ts = [BASE, BASE + 5_000, BASE + 95_000]
    out_ref = ref.process(rows, ts)
    kids = np.array([col.key_id_for(("a",))] * 3, dtype=np.int32)
    enc = np.array([col.dicts["device"].encode("a")] * 3, dtype=np.int32)
    out_col = col.process_columnar(
        kids, np.array(ts, dtype=np.int64),
        {"temp": np.ones(3, np.float32), "device": enc})
    assert by_key(out_ref) == by_key(out_col)
    assert by_key(out_ref)[("a", BASE)]["cnt"] == 2
