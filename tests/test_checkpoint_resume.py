"""Operator-state checkpoint/resume tests.

The reference only checkpoints reader positions (Checkpoint.hs:37-46);
operator state is in-memory (Codegen.hs:374-385), so its restarts
undercount windows. Here snapshots pair state with read LSNs atomically
(engine.snapshot, tasks._snapshot_now): a kill-restarted query must
produce EXACTLY the windows of an uninterrupted run. Covers regression
(d) from round-3 ADVICE: checkpoints committed before windows close.
"""

import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.engine.snapshot import restore_executor, snapshot_executor
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached
from hstream_tpu.server.tasks import QueryTask, snapshot_key
from hstream_tpu.sql.codegen import make_executor, stream_codegen

BASE = 1_700_000_000_000


# ---- unit: snapshot/restore roundtrips --------------------------------------


def _run_both(sql, batches, split):
    """Feed `batches` to (a) one uninterrupted executor and (b) one that
    is snapshotted/restored after `split` batches; return both output
    row lists."""
    plan = stream_codegen(sql)
    sample = batches[0][0]
    a = make_executor(plan, sample_rows=sample)
    b = make_executor(plan, sample_rows=sample)

    def feed(ex, rows, ts, stream=None):
        if stream is not None:
            return ex.process(rows, ts, stream=stream)
        return ex.process(rows, ts)

    out_a, out_b = [], []
    for i, (rows, ts, *origin) in enumerate(batches):
        stream = origin[0] if origin else None
        out_a.extend(feed(a, rows, ts, stream))
        if i == split:
            blob = snapshot_executor(b, {"mark": 42})
            b, extra = restore_executor(plan, blob)
            assert extra["mark"] == 42
        out_b.extend(feed(b, rows, ts, stream))
    return out_a, out_b


def _norm(rows):
    return sorted(
        tuple(sorted((k, round(v, 6) if isinstance(v, float) else v)
                     for k, v in r.items()))
        for r in rows)


def test_lattice_roundtrip_mid_window():
    sql = ("SELECT device, COUNT(*) AS c, SUM(temp) AS s, MIN(temp) AS lo "
           "FROM s GROUP BY device, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    batches = [
        ([{"device": "a", "temp": 1.0}, {"device": "b", "temp": 5.0}],
         [BASE, BASE + 100]),
        # snapshot lands here: window still open with a=1, b=1
        ([{"device": "a", "temp": 2.0}], [BASE + 5000]),
        ([{"device": "c", "temp": 9.0}], [BASE + 15_000]),  # closes win 1
        ([{"device": "c", "temp": 1.0}], [BASE + 30_000]),  # closes win 2
    ]
    out_a, out_b = _run_both(sql, batches, split=0)
    assert _norm(out_a) == _norm(out_b)
    closed = [r for r in out_b if r.get("winStart") == BASE]
    got = {r["device"]: r for r in closed}
    assert got["a"]["c"] == 2 and got["a"]["s"] == pytest.approx(3.0)
    assert got["a"]["lo"] == pytest.approx(1.0)


def test_lattice_roundtrip_sketches_and_strings():
    sql = ("SELECT k, APPROX_COUNT_DISTINCT(v) AS d, AVG(v) AS m FROM s "
           "WHERE tag = 'keep' GROUP BY k, "
           "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    rows1 = [{"k": "x", "v": float(i % 7), "tag": "keep"} for i in range(40)]
    rows1 += [{"k": "x", "v": 99.0, "tag": "drop"}]
    rows2 = [{"k": "x", "v": float(i % 5), "tag": "keep"} for i in range(20)]
    batches = [
        (rows1, [BASE + i for i in range(len(rows1))]),
        (rows2, [BASE + 2000 + i for i in range(len(rows2))]),
        ([{"k": "z", "v": 0.0, "tag": "keep"}], [BASE + 20_000]),
    ]
    out_a, out_b = _run_both(sql, batches, split=0)
    assert _norm(out_a) == _norm(out_b)


def test_session_roundtrip():
    sql = ("SELECT user, COUNT(*) AS c FROM s GROUP BY user, "
           "SESSION (INTERVAL 5 SECOND) GRACE BY INTERVAL 0 SECOND "
           "EMIT CHANGES;")
    batches = [
        ([{"user": "u1"}, {"user": "u2"}], [BASE, BASE + 1000]),
        ([{"user": "u1"}], [BASE + 3000]),   # extends u1's session
        ([{"user": "u1"}], [BASE + 40_000]),  # closes earlier sessions
    ]
    out_a, out_b = _run_both(sql, batches, split=0)
    assert _norm(out_a) == _norm(out_b)


def test_join_roundtrip():
    sql = ("SELECT l.k, COUNT(*) AS c FROM l INNER JOIN r "
           "WITHIN (INTERVAL 5 SECOND) ON l.k = r.k "
           "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    batches = [
        ([{"k": "a", "x": 1.0}], [BASE], "l"),
        # snapshot: left row waiting in the side store
        ([{"k": "a", "y": 2.0}], [BASE + 1000], "r"),  # joins with left
        ([{"k": "a", "x": 3.0}], [BASE + 30_000], "l"),
    ]
    out_a, out_b = _run_both(sql, batches, split=0)
    assert _norm(out_a) == _norm(out_b)
    assert any(r.get("c") == 1 for r in out_b)  # the join happened


def test_stateless_roundtrip():
    sql = "SELECT a FROM s WHERE a > 1 EMIT CHANGES;"
    batches = [
        ([{"a": 1}, {"a": 2}], [BASE, BASE + 1]),
        ([{"a": 3}], [BASE + 2]),
    ]
    out_a, out_b = _run_both(sql, batches, split=0)
    assert _norm(out_a) == _norm(out_b)
    assert len(out_b) == 2


# ---- e2e: kill-restart equals uninterrupted run -----------------------------


def _stub_for(server_ctx):
    server, ctx = server_ctx
    channel = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    return HStreamApiStub(channel), channel


def append_rows(stub, stream, rows, ts):
    req = pb.AppendRequest(stream_name=stream)
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    return stub.Append(req)


def _poll_view(stub, view, pred, timeout=30):
    rows = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=f"SELECT * FROM {view};"))
        rows = [rec.struct_to_dict(s) for s in resp.result_set]
        if pred(rows):
            return rows
        time.sleep(0.2)
    return rows


def _kill_restart_flow(stub, ctx, *, stream, view, restart):
    """Shared flow: ingest A -> wait snapshot -> ingest A2 (past the
    snapshot, regression (d)) -> crash -> restart -> ingest B -> the
    closed window must hold A + A2 + B contributions exactly once."""
    stub.CreateStream(pb.Stream(stream_name=stream))
    QueryTask.snapshot_interval_ms = 50
    try:
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=f"CREATE VIEW {view} AS SELECT city, COUNT(*) AS c "
                      f"FROM {stream} GROUP BY city, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        qid = f"view-{view}"
        wait_attached(ctx, qid)
        # A: 2 sf + 1 la into window [BASE, BASE+10s); stays open
        append_rows(stub, stream,
                    [{"city": "sf"}, {"city": "sf"}, {"city": "la"}],
                    [BASE, BASE + 10, BASE + 20])
        # wait until a snapshot covering A exists
        deadline = time.time() + 20
        while time.time() < deadline:
            blob = ctx.store.meta_get(snapshot_key(qid))
            if blob is not None:
                live = _poll_view(stub, view,
                                  lambda rs: any(r.get("c") == 2
                                                 for r in rs), timeout=1)
                if any(r.get("c") == 2 for r in live):
                    break
            time.sleep(0.05)
        assert ctx.store.meta_get(snapshot_key(qid)) is not None
        # A2: processed but NOT snapshotted (interval cranked up) —
        # the read checkpoint must NOT advance past the state snapshot
        task = ctx.running_queries[qid]
        task.snapshot_interval_ms = 10**9
        append_rows(stub, stream, [{"city": "sf"}], [BASE + 30])
        _poll_view(stub, view,
                   lambda rs: any(r.get("c") == 3 for r in rs))
        # crash: no graceful snapshot
        task.stop(crash=True)
        restart(qid)
        wait_attached(ctx, qid)
        # B: one more sf + the closer
        append_rows(stub, stream, [{"city": "sf"}], [BASE + 40])
        append_rows(stub, stream, [{"city": "zz"}], [BASE + 30_000])
        rows = _poll_view(
            stub, view,
            lambda rs: any(r.get("city") == "sf" and r.get("c") == 4
                           and r.get("winStart") == BASE for r in rs))
        closed = {r["city"]: r["c"] for r in rows
                  if r.get("winStart") == BASE}
        # 4 sf (2 A + 1 A2 replayed once + 1 B), 1 la — no undercount,
        # no double count
        assert closed.get("sf") == 4, rows
        assert closed.get("la") == 1, rows
    finally:
        QueryTask.snapshot_interval_ms = 1000


def test_kill_restart_query_task_mem():
    """Crash + RestartQuery on the mem store backend."""
    server, ctx = serve("127.0.0.1", 0, "mem://")
    stub, channel = _stub_for((server, ctx))
    try:
        def restart(qid):
            stub.RestartQuery(pb.RestartQueryRequest(id=qid))

        _kill_restart_flow(stub, ctx, stream="krs", view="krv",
                           restart=restart)
    finally:
        channel.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_clean_restart_server_native(tmp_path):
    """A GRACEFUL server restart (ctx.shutdown detaches tasks: snapshot
    + status stays RUNNING) must also resume views — not only crashes."""
    store_dir = str(tmp_path / "store")
    server, ctx = serve("127.0.0.1", 0, store_dir)
    stub, channel = _stub_for((server, ctx))
    try:
        stub.CreateStream(pb.Stream(stream_name="crs"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW crv AS SELECT city, COUNT(*) AS c "
                      "FROM crs GROUP BY city, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        wait_attached(ctx, "view-crv")
        append_rows(stub, "crs", [{"city": "sf"}, {"city": "la"}],
                    [BASE, BASE + 10])
        _poll_view(stub, "crv", lambda rs: len(rs) >= 2)
        channel.close()
        server.stop(grace=1)
        ctx.shutdown()  # graceful: detach + final snapshot

        server, ctx = serve("127.0.0.1", 0, store_dir)
        stub, channel = _stub_for((server, ctx))
        wait_attached(ctx, "view-crv")
        append_rows(stub, "crs", [{"city": "zz"}], [BASE + 30_000])
        rows = _poll_view(
            stub, "crv",
            lambda rs: any(r.get("city") == "sf" and r.get("c") == 1
                           and r.get("winStart") == BASE for r in rs))
        closed = {r["city"]: r["c"] for r in rows
                  if r.get("winStart") == BASE}
        assert closed.get("sf") == 1 and closed.get("la") == 1, rows
    finally:
        channel.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_kill_restart_server_native(tmp_path):
    """Crash the task, then restart the WHOLE server on the same native
    store directory: boot-time resume_persisted must relaunch the view
    with its snapshotted state."""
    store_dir = str(tmp_path / "store")
    server, ctx = serve("127.0.0.1", 0, store_dir)
    stub, channel = _stub_for((server, ctx))
    QueryTask.snapshot_interval_ms = 50
    try:
        stub.CreateStream(pb.Stream(stream_name="nks"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW nkv AS SELECT city, COUNT(*) AS c "
                      "FROM nks GROUP BY city, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        qid = "view-nkv"
        wait_attached(ctx, qid)
        append_rows(stub, "nks",
                    [{"city": "sf"}, {"city": "sf"}, {"city": "la"}],
                    [BASE, BASE + 10, BASE + 20])
        deadline = time.time() + 20
        while time.time() < deadline:
            if ctx.store.meta_get(snapshot_key(qid)) is not None:
                live = _poll_view(stub, "nkv",
                                  lambda rs: any(r.get("c") == 2
                                                 for r in rs), timeout=1)
                if any(r.get("c") == 2 for r in live):
                    break
            time.sleep(0.05)
        task = ctx.running_queries[qid]
        task.stop(crash=True)  # crash the query thread
        channel.close()
        server.stop(grace=1)
        ctx.shutdown()  # closes the native store

        # full server restart on the same directory
        server, ctx = serve("127.0.0.1", 0, store_dir)
        stub, channel = _stub_for((server, ctx))
        wait_attached(ctx, qid)  # boot resume relaunches the view task
        append_rows(stub, "nks", [{"city": "sf"}], [BASE + 40])
        append_rows(stub, "nks", [{"city": "zz"}], [BASE + 30_000])
        rows = _poll_view(
            stub, "nkv",
            lambda rs: any(r.get("city") == "sf" and r.get("c") == 3
                           and r.get("winStart") == BASE for r in rs))
        closed = {r["city"]: r["c"] for r in rows
                  if r.get("winStart") == BASE}
        assert closed.get("sf") == 3, rows
        assert closed.get("la") == 1, rows
    finally:
        QueryTask.snapshot_interval_ms = 1000
        channel.close()
        server.stop(grace=1)
        ctx.shutdown()
