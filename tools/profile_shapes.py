"""Pin down: which shapes transfer slowly, and what actually forces
execution through the tunnel."""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, reps=3):
    fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def main():
    N = 2 * 1024 * 1024  # 8MB of int32
    shapes = [(N,), (4, N // 4), (N // 4, 4), (8, N // 8), (16, N // 16),
              (128, N // 128), (N // 128, 128)]
    for sh in shapes:
        buf = np.zeros(sh, np.int32)
        d = t(lambda b=buf: jax.device_put(b).block_until_ready())
        print(f"put {sh!s:>18}: {d*1e3:7.1f} ms -> {buf.nbytes/d/1e6:8.1f} MB/s")

    # does block_until_ready force execution? compare with explicit fetch
    # analyze: ok retrace-uncached-jit — one-shot profiling CLI
    @jax.jit
    def burn(x):
        def body(i, acc):
            return acc @ acc * 1e-3 + x
        return jax.lax.fori_loop(0, 200, body, x)

    x = jax.device_put(np.eye(4096, dtype=np.float32))
    b1 = t(lambda: burn(x).block_until_ready())
    print(f"burn + block_until_ready: {b1*1e3:.1f} ms")
    b2 = t(lambda: np.asarray(burn(x)[0, 0]))
    print(f"burn + fetch scalar slice: {b2*1e3:.1f} ms")

    # fetch cost: tiny slice of a big resident array vs whole array
    big = jax.device_put(np.zeros((1024, 2048), np.float32))
    f1 = t(lambda: np.asarray(big[0, 0]), reps=5)
    print(f"fetch scalar slice of resident: {f1*1e3:.1f} ms")
    f2 = t(lambda: np.asarray(big), reps=5)
    print(f"fetch whole 8MB resident: {f2*1e3:.1f} ms")


if __name__ == "__main__":
    main()
