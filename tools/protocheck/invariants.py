"""The certified invariants.

Three layers, matching what the protocol can promise at each horizon:

* **Transition post-conditions** — checked after every action against
  the record diff: a seizure (ownership claim over someone else's
  record) is legal ONLY through one of the sanctioned doors
  (``seizure-*``); a heartbeat pass leaves every local task backed by
  an owned record naming this node (``fence-post``) and never rewrites
  a peer's record (``hb-foreign-write``); a rebalance only emits
  offers for tasks it has already stopped (``offer-live-task``).
* **State invariants** — checked at every reachable state: record
  shape discipline (a disarmed owner's record must stay legacy — the
  stale-``hb_ms`` misread fix), and the zombie rule: a live node
  running a query its record does not grant must be ARMED (armed
  zombies self-fence on their next tick; a disarmed zombie never
  would — that is "two live owners" made permanent).
* **Convergence** — from every reachable state, the deterministic
  stabilization drive (``Model.stabilize``) must end with every
  RUNNING/rescuable query owned by exactly one live node, no offers
  pending, and no zombies: offered records converge, and no query is
  permanently unowned while a live armed node exists.

The seizure check is deliberately computed from the SPEC, not the
code: the effective lease is ``max(lease_ms, 3*interval_ms)`` (the
clamp PR 17 added) and the heartbeat age is taken from the model's
ground-truth write times, discounted by the worst clock-skew spread.
A mutant that drops the clamp or the fresh-heartbeat refusal therefore
diverges from this spec and produces a counterexample.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hstream_tpu.server.persistence import TaskStatus


@dataclass
class Violation:
    rule: str
    message: str
    details: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"rule": self.rule, "message": self.message,
                "details": self.details}


def _owner(rec: dict | None) -> str | None:
    return None if rec is None else rec.get("node")


def _state(rec: dict | None) -> str:
    return "absent" if rec is None else rec.get("state", "owned")


def check_transition(model, action: tuple,
                     pre: dict[str, tuple[bytes, dict]],
                     post: dict[str, tuple[bytes, dict]]
                     ) -> list[Violation]:
    """Post-conditions of one action, using the PRE-action ground
    truth (call before ``model.update_truth``)."""
    out: list[Violation] = []
    kind = action[0]
    if kind in ("advance", "pause", "resume", "skew", "crash"):
        return out  # these touch no records
    actor = model.nodes[action[1]]
    lease = model.scenario.effective_lease_ms
    spread = model.scenario.max_skew_spread_ms

    changed = [qid for qid in set(pre) | set(post)
               if (pre.get(qid) or (None,))[0]
               != (post.get(qid) or (None,))[0]]

    for qid in sorted(changed):
        pre_rec = pre[qid][1] if qid in pre else None
        post_rec = post[qid][1] if qid in post else None
        if post_rec is None:
            out.append(Violation(
                "record-dropped",
                f"{kind} by {actor.name} deleted the record of {qid}",
                {"query": qid}))
            continue
        post_state = post_rec.get("state", "owned")

        if kind == "hb":
            # a heartbeat refreshes records THIS node owns; any other
            # write from the heartbeat path keeps a peer's lease
            # alive on its behalf (or resurrects a dead record)
            if post_rec.get("node") != actor.name \
                    or post_state != "owned":
                out.append(Violation(
                    "hb-foreign-write",
                    f"heartbeat by {actor.name} rewrote the record "
                    f"of {qid}, which names "
                    f"{post_rec.get('node')!r} ({post_state})",
                    {"query": qid, "record": post_rec}))
            continue

        if post_state == "offered":
            if kind != "reb":
                out.append(Violation(
                    "offer-outside-rebalance",
                    f"{kind} by {actor.name} wrote an offered record "
                    f"for {qid}; only the rebalance stage offers",
                    {"query": qid, "record": post_rec}))
                continue
            if post_rec.get("src") != actor.name:
                out.append(Violation(
                    "offer-foreign-src",
                    f"rebalance by {actor.name} wrote an offer for "
                    f"{qid} with src {post_rec.get('src')!r}",
                    {"query": qid, "record": post_rec}))
            if qid in actor.running:
                # "the local task is dead before the offer is
                # visible" — otherwise the offer target and the
                # offerer are two live owners for a whole lease
                out.append(Violation(
                    "offer-live-task",
                    f"rebalance by {actor.name} offered {qid} away "
                    f"while still running it locally",
                    {"query": qid}))
            continue

        # owned post-record: a refresh of the actor's own ownership
        # is free; anything else is a SEIZURE and must come through a
        # sanctioned door
        if post_rec.get("node") != actor.name:
            out.append(Violation(
                "foreign-owner-write",
                f"{kind} by {actor.name} wrote an owned record for "
                f"{qid} naming {post_rec.get('node')!r}",
                {"query": qid, "record": post_rec}))
            continue
        if pre_rec is not None and _owner(pre_rec) == actor.name \
                and _state(pre_rec) == "owned":
            continue  # refresh / re-claim of an already-owned record
        if pre_rec is None:
            continue  # recordless claim (boot or live): sanctioned
        if _state(pre_rec) == "offered" \
                and pre_rec.get("node") == actor.name:
            continue  # the offer explicitly named this node
        if "hb_ms" not in pre_rec:
            # legacy record: the owner may be alive RIGHT NOW and
            # will never heartbeat — only a boot (fresh epoch over a
            # genuinely dead predecessor) may apply the epoch rule
            if kind != "reboot":
                out.append(Violation(
                    "seizure-legacy-live",
                    f"{kind} by {actor.name} seized the legacy "
                    f"record of {qid} from "
                    f"{pre_rec.get('node')!r}; the live sweep must "
                    f"never apply the epoch rule to legacy records",
                    {"query": qid, "prev": pre_rec}))
            elif int(pre_rec.get("epoch", 0)) >= actor.ctx.boot_epoch:
                out.append(Violation(
                    "seizure-epoch",
                    f"reboot of {actor.name} (epoch "
                    f"{actor.ctx.boot_epoch}) seized {qid} from an "
                    f"equal-or-newer epoch "
                    f"{pre_rec.get('epoch')}",
                    {"query": qid, "prev": pre_rec}))
            continue
        # heartbeated record: legal only once the TRUE stamp age has
        # lapsed the effective lease, discounted by the worst skew
        # spread (an observed lapse can under-read true age by at
        # most the spread)
        writer, stamp_true_ms = model.truth.get(qid, (None, 0))
        true_age = model.clock.true_ms - stamp_true_ms
        if true_age <= lease - spread:
            out.append(Violation(
                "seizure-fresh-lease",
                f"{kind} by {actor.name} seized {qid} from "
                f"{pre_rec.get('node')!r} ({_state(pre_rec)}) at true "
                f"heartbeat age {true_age}ms <= effective lease "
                f"{lease}ms - skew spread {spread}ms",
                {"query": qid, "prev": pre_rec, "true_age_ms": true_age,
                 "effective_lease_ms": lease, "skew_spread_ms": spread}))

    if kind == "hb":
        # fence post-condition: after a heartbeat pass every local
        # task is backed by an owned record naming this node — a
        # definitive heartbeat failure must have self-fenced
        for qid in sorted(actor.running):
            rec = post.get(qid, (None, None))[1]
            if rec is None or rec.get("node") != actor.name \
                    or rec.get("state", "owned") != "owned":
                out.append(Violation(
                    "fence-post",
                    f"after heartbeat, {actor.name} still runs {qid} "
                    f"but the record "
                    f"{'is gone' if rec is None else 'names ' + repr(rec.get('node'))}"
                    f" — the loser did not self-fence",
                    {"query": qid, "record": rec}))
    return out


def check_state(model) -> list[Violation]:
    """Invariants of every reachable state."""
    out: list[Violation] = []
    records = model.sched_records()
    epochs = [n.ctx.boot_epoch for n in model.nodes]
    if len(set(epochs)) != len(epochs):  # pragma: no cover — model bug
        out.append(Violation("epoch-collision",
                             f"duplicate boot epochs {epochs}", {}))
    max_epoch = max(epochs)
    for qid, (_raw, rec) in sorted(records.items()):
        if not isinstance(rec, dict):
            out.append(Violation(
                "record-shape",
                f"record of {qid} is not valid JSON", {"query": qid}))
            continue
        state = rec.get("state", "owned")
        owner_idx = model.name_to_idx.get(rec.get("node"))
        if owner_idx is None or state not in ("owned", "offered") \
                or not isinstance(rec.get("epoch"), int) \
                or int(rec.get("epoch", 0)) > max_epoch:
            out.append(Violation(
                "record-shape",
                f"malformed record for {qid}: {rec}",
                {"query": qid, "record": rec}))
            continue
        if state == "offered" and ("src" not in rec
                                   or "hb_ms" not in rec):
            out.append(Violation(
                "offer-shape",
                f"offered record for {qid} lacks src/hb_ms: {rec} — "
                f"an offer without a fresh heartbeat is instantly "
                f"seizable by any node",
                {"query": qid, "record": rec}))
            continue
        if state == "owned":
            owner = model.nodes[owner_idx]
            if not owner.armed and "hb_ms" in rec:
                out.append(Violation(
                    "disarmed-stamp",
                    f"record of {qid} is owned by disarmed "
                    f"{owner.name} but carries hb_ms — the stamp can "
                    f"never refresh and reads as a lapsed lease to "
                    f"every armed peer",
                    {"query": qid, "record": rec}))
    for n in model.nodes:
        if not n.alive:
            continue
        for qid in sorted(n.running):
            rec = records.get(qid, (None, None))[1]
            granted = (isinstance(rec, dict)
                       and rec.get("node") == n.name
                       and rec.get("state", "owned") == "owned")
            if not granted and not n.armed:
                # an armed zombie self-fences on its next heartbeat
                # tick; a disarmed one never ticks — a permanent
                # second live owner
                out.append(Violation(
                    "zombie-disarmed",
                    f"disarmed {n.name} runs {qid} but the record "
                    f"{'is gone' if rec is None else 'names ' + repr(_owner(rec))}"
                    f"; it can never self-fence",
                    {"query": qid, "node": n.name, "record": rec}))
    return out


def check_convergence(model) -> list[Violation]:
    """Asserted after ``Model.stabilize``: ownership has quiesced."""
    out: list[Violation] = []
    records = model.sched_records()
    alive_armed = any(n.alive and n.armed for n in model.nodes)
    if not alive_armed:
        return out
    runners: dict[str, list[str]] = {}
    for n in model.nodes:
        if not n.alive:
            continue
        for qid in n.running:
            runners.setdefault(qid, []).append(n.name)
    for info in model.persistence.get_queries():
        qid = info.query_id
        if info.status not in (TaskStatus.RUNNING, TaskStatus.CREATED):
            continue
        rec = records.get(qid, (None, None))[1]
        who = sorted(runners.get(qid, []))
        if rec is None:
            if info.status == TaskStatus.CREATED:
                continue  # recordless CREATED: boot-rescue only (the
                # creator is mid-write; documented model boundary)
            out.append(Violation(
                "convergence-unowned",
                f"{qid} (RUNNING) has no owner record after "
                f"stabilization with live armed nodes present",
                {"query": qid}))
            continue
        if not isinstance(rec, dict):
            continue  # record-shape already flagged
        if "hb_ms" not in rec:
            owner_idx = model.name_to_idx.get(rec.get("node"))
            owner = (model.nodes[owner_idx]
                     if owner_idx is not None else None)
            if owner is None or not owner.alive:
                continue  # dead legacy owner: boot-time adoption is
                # the rescue path for legacy records (by design)
            if who != [owner.name]:
                out.append(Violation(
                    "convergence-legacy",
                    f"{qid} is owned by live disarmed {owner.name} "
                    f"but runs on {who}",
                    {"query": qid, "runners": who}))
            continue
        if rec.get("state", "owned") == "offered":
            out.append(Violation(
                "convergence-offer",
                f"the offer of {qid} to {rec.get('node')!r} never "
                f"resolved: offered records must converge",
                {"query": qid, "record": rec}))
            continue
        owner_idx = model.name_to_idx.get(rec.get("node"))
        owner = model.nodes[owner_idx] if owner_idx is not None else None
        if owner is None or not owner.alive \
                or qid not in owner.running:
            out.append(Violation(
                "convergence-unowned",
                f"{qid} is recorded to {rec.get('node')!r} but "
                f"{'that node is dead' if owner is None or not owner.alive else 'it does not run the task'}"
                f" after stabilization",
                {"query": qid, "record": rec, "runners": who}))
            continue
        if who != [owner.name]:
            out.append(Violation(
                "convergence-two-owners",
                f"{qid} runs on {who} but the record grants only "
                f"{owner.name} — a second live owner survived "
                f"stabilization",
                {"query": qid, "runners": who}))
    for n in model.nodes:
        if not n.alive:
            continue
        for qid in sorted(n.running):
            rec = records.get(qid, (None, None))[1]
            if not (isinstance(rec, dict) and rec.get("node") == n.name
                    and rec.get("state", "owned") == "owned"):
                out.append(Violation(
                    "convergence-zombie",
                    f"{n.name} still runs {qid} without a granting "
                    f"record after stabilization",
                    {"query": qid, "node": n.name, "record": rec}))
    return out
