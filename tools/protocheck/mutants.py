"""Mutation gate: each PR 17 review fix, mechanically reverted.

A model checker that has never seen a violation proves nothing about
itself. Every entry here is a pre-fix copy of one protocol function —
the exact code shape the PR 17 review round replaced — patched over
the live module for one exploration. The gate (``python -m
tools.protocheck --mutants``) requires a counterexample trace for
every mutant: if a revert stops producing one, either the invariant
rotted or the scenario no longer reaches the bug, and CI fails.

The two remaining PR 17 fixes (the torn pack-attach lock and the
FAILED-status clobber serialization) are THREAD-level races inside one
function; protocheck's actions are atomic whole-function steps, so
those stay certified by PR 13's lockorder/atomicity passes instead —
see the README "Protocol certification" section.

Reverted bodies are verbatim copies minus the fix (marked
``REVERTED:``); they drift-check against the live functions only
through the gate itself, which is the point.
"""

from __future__ import annotations

import contextlib
import json
import threading
from collections import deque
from dataclasses import dataclass

from hstream_tpu.placer import core as placer_core
from hstream_tpu.server import scheduler
from hstream_tpu.store.versioned import VersionMismatch


@contextlib.contextmanager
def _swap(obj, attr: str, repl):
    orig = getattr(obj, attr)
    setattr(obj, attr, repl)
    try:
        yield
    finally:
        setattr(obj, attr, orig)


@dataclass
class Mutant:
    name: str
    fix: str        # the review fix this reverts
    scenario: str   # scenario expected to produce the counterexample
    kind: str       # "sched" (ownership model) or "replica"
    patch: object   # zero-arg callable returning a context manager


# ---- reverted bodies (scheduler) --------------------------------------------


def _try_adopt_live_no_refusal(ctx, query_id: str, lease_ms: int) -> bool:
    """try_adopt_live without the fresh-heartbeat refusal: any
    non-offered record is claimable on the epoch rule alone."""
    cur = ctx.config.get(scheduler._key(query_id))
    me = scheduler.node_name(ctx)
    if cur is None:
        try:
            ctx.config.put(scheduler._key(query_id),
                           scheduler._owned_record(ctx))
            return True
        except VersionMismatch:
            return False
    version, raw = cur
    try:
        rec = json.loads(raw)
    except ValueError:
        rec = {"node": "?", "epoch": 0}
    state = rec.get("state", "owned")
    if rec.get("node") == me and state == "owned":
        return False
    offered_to_me = state == "offered" and rec.get("node") == me
    if not offered_to_me:
        age = scheduler.owner_heartbeat_age_ms(rec)
        if age is None:
            if int(rec.get("epoch", 0)) >= ctx.boot_epoch:
                return False
        # REVERTED: `elif age <= int(lease_ms): return False` — the
        # fresh-heartbeat refusal
    try:
        ctx.config.put(scheduler._key(query_id),
                       scheduler._owned_record(ctx),
                       base_version=version)
        return True
    except VersionMismatch:
        return False


def _heartbeat_owned_no_fence(self) -> None:
    """_heartbeat_owned that ignores definitive heartbeat failure:
    the slow owner keeps its task — two live owners."""
    ctx = self.ctx
    owned = set(getattr(ctx, "running_queries", {}))
    sup = getattr(ctx, "supervisor", None)
    if sup is not None:
        st = sup.status()
        owned.update(st.get("pending", {}))
    for qid in sorted(owned):
        scheduler.heartbeat_assignment(ctx, qid)
        # REVERTED: `if not ...: self._self_fence(qid)`


def _owned_record_always_stamped(ctx) -> bytes:
    """_owned_record that stamps hb_ms/state even with the placer
    disarmed — the stamp can never refresh, so armed peers misread it
    as a lapsed lease."""
    # REVERTED: the `placer is not None and placer.armed` gate
    return json.dumps({"node": scheduler.node_name(ctx),
                       "epoch": ctx.boot_epoch,
                       "hb_ms": scheduler.now_ms(),
                       "state": "owned"}).encode()


def _adopt_sweep(self, *, legacy_skip: bool, created_rescue: bool
                 ) -> None:
    """_adopt_sweep body with the two reverts toggleable."""
    from hstream_tpu.server.persistence import TaskStatus

    ctx = self.ctx
    if getattr(ctx.store, "fenced_by", None) is not None:
        return
    me = scheduler.node_name(ctx)
    for info in ctx.persistence.get_queries():
        qid = info.query_id
        if qid in ctx.running_queries:
            continue
        rec = scheduler.assignment(ctx, qid)
        state = (rec or {}).get("state", "owned")
        offered_to_me = (rec is not None and state == "offered"
                         and rec.get("node") == me)
        if info.status == TaskStatus.CREATED and not offered_to_me:
            if created_rescue:
                age = scheduler.owner_heartbeat_age_ms(rec)
                if age is None or age <= self.lease_ms:
                    continue
            else:
                # REVERTED: the lapsed-heartbeat rescue of orphaned
                # CREATED queries
                continue
        if info.status not in (TaskStatus.CREATED, TaskStatus.RUNNING):
            continue
        if rec is not None and rec.get("node") == me \
                and state == "owned":
            continue
        if legacy_skip:
            if rec is not None and rec.get("node") != me \
                    and "hb_ms" not in rec:
                continue
        # else REVERTED: the legacy-record (disarmed live peer) skip
        if not scheduler.adoption_allowed(ctx, qid):
            continue
        if not scheduler.try_adopt_live(ctx, qid, self.lease_ms):
            continue
        reason = "offered" if offered_to_me else (
            "unowned" if rec is None else "lease_lapsed")
        self._count("queries_adopted", qid)
        self._decide("adopt", qid, target=me, reason=reason,
                     prev_owner=(rec or {}).get("node"))
        self._resume_adopted(info)


def _adopt_sweep_no_legacy_skip(self) -> None:
    _adopt_sweep(self, legacy_skip=False, created_rescue=True)


def _adopt_sweep_no_created_rescue(self) -> None:
    _adopt_sweep(self, legacy_skip=True, created_rescue=False)


def _placer_init_unclamped(self, ctx, *, interval_ms=None,
                           lease_ms=placer_core.DEFAULT_LEASE_MS):
    """Placer.__init__ without the lease >= 3x interval clamp."""
    self.ctx = ctx
    self.interval_ms = interval_ms
    self.lease_ms = int(lease_ms)
    self.armed = bool(interval_ms) and int(interval_ms) > 0
    # REVERTED: `if self.lease_ms < 3 * interval_ms: clamp`
    self.resume_fn = None
    self.last_decision = None
    self._decisions = deque(maxlen=64)
    self._stop_evt = threading.Event()
    self._thread = None
    self.ticks = 0


def _heartbeat_assignment_no_owner_check(ctx, query_id: str) -> bool:
    """heartbeat_assignment that refreshes whatever record exists —
    a fenced loser keeps a peer's (or its own stale) lease alive."""
    for _ in range(4):
        cur = ctx.config.get(scheduler._key(query_id))
        if cur is None:
            return False
        version, raw = cur
        try:
            rec = json.loads(raw)
        except ValueError:
            return False
        # REVERTED: `if rec.get("node") != me or state != "owned":
        # return False` — the ownership check before the stamp
        rec["hb_ms"] = scheduler.now_ms()
        rec["epoch"] = ctx.boot_epoch
        try:
            ctx.config.put(scheduler._key(query_id),
                           json.dumps(rec).encode(),
                           base_version=version)
            return True
        except VersionMismatch:
            continue
    return True


# ---- reverted bodies (replica) ----------------------------------------------


def _promote_no_epoch_guard(self, request, context):
    """FollowerService.Promote without the `epoch <= current` refusal:
    a raced or stale second promotion succeeds — epochs can move
    backwards and two leaders coexist."""
    from hstream_tpu.proto import api_pb2 as pb

    with self._lock:
        if self._broken is not None:
            context.abort(None, "broken")
        # REVERTED: `if request.epoch <= self._epoch: return
        # PromoteResponse(ok=False, ...)`
        self._promote_locked(int(request.epoch), request.leader_addr,
                             request.promoted_by or "operator")
        return pb.PromoteResponse(ok=True, epoch=self._epoch,
                                  applied_seq=self.applied_seq,
                                  node_id=self.node_id)


def _replicate_no_duel_resolution(self, request, context):
    """FollowerService.Replicate where a dueling same-epoch promoted
    leader is ALWAYS fenced instead of resolving to the higher node
    id: two leaders at one epoch persist forever."""
    from hstream_tpu.store import replica as replica_mod

    with self._lock:
        if self._broken is not None:
            context.abort(None, f"broken: {self._broken}")
        if request.epoch < self._epoch:
            return self._fenced_response(request)
        if request.epoch > self._epoch:
            self._accept_leader_locked(request)
        elif request.leader_id:
            if self._is_leader and request.leader_id != self.node_id:
                # REVERTED: `if request.leader_id > self.node_id:
                # accept/demote` — dueling promotions never resolve
                return self._fenced_response(request)
            elif self._leader_id is None:
                self._accept_leader_locked(request)
            elif self._leader_id != request.leader_id:
                context.abort(None, "two same-epoch leaders")
        applied = self.applied_seq
        for e in request.entries:
            if e.seq and e.seq != applied + 1:
                break
            replica_mod._apply(self.local, e)
            applied = self.local.append(replica_mod.OPLOG_ID,
                                        replica_mod._encode_entry(e))
        from hstream_tpu.proto import api_pb2 as pb
        return pb.ReplicateResponse(applied_seq=applied,
                                    epoch=self._epoch)


# ---- the registry -----------------------------------------------------------


def _sched_patch(*swaps):
    def make():
        @contextlib.contextmanager
        def cm():
            with contextlib.ExitStack() as s:
                for obj, attr, repl in swaps:
                    s.enter_context(_swap(obj, attr, repl))
                yield
        return cm()
    return make


def _replica_patch(attr, repl):
    def make():
        from hstream_tpu.store.replica import FollowerService
        return _swap(FollowerService, attr, repl)
    return make


MUTANTS: list[Mutant] = [
    Mutant(
        name="fresh-heartbeat-refusal",
        fix="try_adopt_live refuses any record with a fresh "
            "heartbeat, whatever its epoch",
        scenario="kill-2", kind="sched",
        patch=_sched_patch((scheduler, "try_adopt_live",
                            _try_adopt_live_no_refusal))),
    Mutant(
        name="no-self-fence",
        fix="a definitive heartbeat failure self-fences the local "
            "task (double-owner on slow heartbeat)",
        scenario="kill-2", kind="sched",
        patch=_sched_patch((placer_core.Placer, "_heartbeat_owned",
                            _heartbeat_owned_no_fence))),
    Mutant(
        name="disarmed-stamp",
        fix="disarmed servers write legacy records — a stamp they "
            "can never refresh misreads as a lapsed lease",
        scenario="mixed-2", kind="sched",
        patch=_sched_patch((scheduler, "_owned_record",
                            _owned_record_always_stamped))),
    Mutant(
        name="legacy-epoch-adopt",
        fix="the live adopt sweep never applies the epoch rule to "
            "legacy records of (possibly live) disarmed peers",
        scenario="mixed-2", kind="sched",
        patch=_sched_patch((placer_core.Placer, "_adopt_sweep",
                            _adopt_sweep_no_legacy_skip))),
    Mutant(
        name="lease-unclamped",
        fix="the heartbeat lease is clamped to >= 3x the placer "
            "interval so a delayed tick cannot read as owner death",
        scenario="clamp-2", kind="sched",
        patch=_sched_patch((placer_core.Placer, "__init__",
                            _placer_init_unclamped))),
    Mutant(
        name="created-no-rescue",
        fix="orphaned CREATED queries (creator or offer target died) "
            "are rescued once the record's heartbeat lapses",
        scenario="created-2", kind="sched",
        patch=_sched_patch((placer_core.Placer, "_adopt_sweep",
                            _adopt_sweep_no_created_rescue))),
    Mutant(
        name="hb-foreign-write",
        fix="heartbeat_assignment refreshes only records this node "
            "owns — a fenced loser must not keep a lease alive",
        scenario="kill-2", kind="sched",
        patch=_sched_patch((scheduler, "heartbeat_assignment",
                            _heartbeat_assignment_no_owner_check))),
    Mutant(
        name="promote-no-epoch-guard",
        fix="Promote refuses an epoch <= the follower's (a raced "
            "second promotion is a clean refusal, not a second "
            "leader)",
        scenario="replica-2", kind="replica",
        patch=_replica_patch("Promote", _promote_no_epoch_guard)),
    Mutant(
        name="duel-no-resolution",
        fix="dueling same-epoch promoted leaders resolve "
            "deterministically (higher node id wins on contact)",
        scenario="replica-2", kind="replica",
        patch=_replica_patch("Replicate", _replicate_no_duel_resolution)),
]

BY_NAME = {m.name: m for m in MUTANTS}
