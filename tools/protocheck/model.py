"""The node-action model: real protocol code under a virtual clock.

A :class:`Model` is N :class:`Node` harnesses around ONE shared
in-memory CAS config store (the real :class:`VersionedConfigStore`
over a dict meta-KV — the same code path a mem:// or native store
serves in production) plus a shared persistence table, mirroring the
shared-store placer deployment ``tests/test_placer.py`` exercises.

Each enabled action executes one REAL protocol function atomically:

``("hb", i)``      ``Placer._heartbeat_owned`` — heartbeat + self-fence
``("adopt", i)``   ``Placer._adopt_sweep`` — lease-lapse/offer adoption
``("pub", i)``     publish node ``i``'s cluster record (rebalance input)
``("reb", i)``     ``Placer._rebalance`` — offer one query away
``("crash", i)``   node dies: local tasks gone, records stay
``("reboot", i)``  node returns with a fresh (max+1) boot epoch and
                   runs the ``resume_persisted`` adoption sweep
                   (``scheduler.owner_live`` gate + ``try_adopt``)
``("pause", i)``   node stops ticking but its tasks keep running —
                   the zombie-owner window crash can never produce
``("resume", i)``  paused node ticks again
``("skew", i)``    node ``i``'s clock jumps ahead by its configured
                   skew (one-way, budgeted)
``("advance",)``   virtual time advances one quantum for everyone

Budgets (crashes, pauses, reboots, skews, advances) bound the state
space; the scenario registry at the bottom defines the concrete
2-node / 3-node kill, pause, skew, mixed-armed, rebalance and
created-orphan models the CLI and CI run.
"""

from __future__ import annotations

import contextlib
import json
import logging
from dataclasses import dataclass

from hstream_tpu.placer import core as placer_core
from hstream_tpu.placer import score as placer_score
from hstream_tpu.placer.core import Placer
from hstream_tpu.server import scheduler
from hstream_tpu.server.persistence import QueryInfo, TaskStatus
from hstream_tpu.store.versioned import VersionMismatch, VersionedConfigStore

SCHED_PREFIX = "scheduler/query/"
NODE_PREFIX = "cluster/nodes/"

# virtual epoch base: far from zero so ``max(0, now - hb)`` clamps and
# missing-stamp defaults behave exactly as on a wall clock
BASE_MS = 1_000_000_000


@contextlib.contextmanager
def quiet_protocol_logs():
    """The protocol functions journal adoptions/fences via logging;
    under exploration that is millions of lines. Restores the prior
    level on exit — the checker runs inside the test process and must
    not mute the tree's loggers for later tests."""
    root = logging.getLogger("hstream_tpu")
    before = root.level
    root.setLevel(logging.CRITICAL)
    try:
        yield
    finally:
        root.setLevel(before)


class MetaKV:
    """Dict-backed meta-KV with the CAS primitive VersionedConfigStore
    needs — the in-memory stand-in for the store's meta WAL."""

    def __init__(self):
        self.data: dict[str, bytes] = {}

    def meta_get(self, key: str) -> bytes | None:
        return self.data.get(key)

    def meta_put(self, key: str, value: bytes) -> None:
        self.data[key] = bytes(value)

    def meta_cas(self, key: str, expected: bytes | None,
                 new: bytes) -> bool:
        if self.data.get(key) != expected:
            return False
        self.data[key] = bytes(new)
        return True

    def meta_delete(self, key: str) -> None:
        self.data.pop(key, None)

    def meta_list(self, prefix: str) -> list[str]:
        return sorted(k for k in self.data if k.startswith(prefix))


class VirtualClock:
    """Quantized virtual time with per-node skew. ``active`` names the
    node whose action is executing; every ``now_ms()`` the protocol
    code makes during that action reads that node's (skewed) clock."""

    def __init__(self):
        self.true_ms = 0
        self.skew: dict[int, int] = {}
        self.active: int | None = None

    def read(self) -> int:
        return BASE_MS + self.true_ms + self.skew.get(self.active, 0)


class _TimeShim:
    """Replaces a module's ``time`` import: wall-clock reads come from
    the virtual clock, everything else passes through."""

    def __init__(self, clock: VirtualClock, real):
        self._clock = clock
        self._real = real

    def time(self) -> float:
        return self._clock.read() / 1000.0

    def monotonic(self) -> float:
        return self._clock.read() / 1000.0

    def sleep(self, _s) -> None:  # pragma: no cover — never awaited
        pass

    def __getattr__(self, name):
        return getattr(self._real, name)


class ModelTask:
    """Stand-in for a running query task; records how it was stopped
    (crash-fence vs detach-move) for invariant checks."""

    packed = False

    def __init__(self, query_id: str):
        self.query_id = query_id
        self.stopped: str | None = None

    def stop(self, crash: bool = False, detach: bool = False) -> None:
        self.stopped = "crash" if crash else ("detach" if detach
                                              else "stop")


class ModelPersistence:
    """Shared query table (the placer deployment shares one store, so
    every node reads the same persistence — see tests/test_placer.py)."""

    def __init__(self):
        self._queries: dict[str, QueryInfo] = {}

    def add(self, info: QueryInfo) -> None:
        self._queries[info.query_id] = info

    def get_queries(self) -> list[QueryInfo]:
        return [self._queries[k] for k in sorted(self._queries)]

    def get_query(self, query_id: str) -> QueryInfo:
        return self._queries[query_id]

    def set_query_status(self, query_id: str, status: int) -> None:
        self._queries[query_id].status = status

    def statuses(self) -> tuple[tuple[str, int], ...]:
        return tuple((k, self._queries[k].status)
                     for k in sorted(self._queries))


class ModelCtx:
    """The slice of ServerContext the protocol functions read."""

    def __init__(self):
        self.flow = None
        self.events = None
        self.supervisor = None
        self.stats = None
        self.pack_pool = None


class _ModelStore:
    def __init__(self):
        self.fenced_by = None


@dataclass
class NodeSpec:
    armed: bool = True
    skew_ms: int = 0


@dataclass
class QuerySpec:
    qid: str
    owner: int | None = None       # node index that owns + runs it
    status: int = TaskStatus.RUNNING
    offered_to: int | None = None  # initial record is an offer
    src: int = 0                   # offering node for offered records


@dataclass
class Scenario:
    """One bounded model. ``lease_ms`` is the CONFIGURED lease; the
    invariants compute the effective lease max(lease, 3*interval)
    themselves, so a mutant that drops the placer's clamp diverges
    from the spec and is caught."""

    name: str
    description: str
    nodes: tuple = (NodeSpec(), NodeSpec())
    queries: tuple = (QuerySpec("q1", owner=0),)
    interval_ms: int = 1000
    lease_ms: int = 3000
    quantum_ms: int = 2000
    advances: int = 4
    crashes: tuple = ()   # per-node crash budget
    reboots: tuple = ()   # per-node reboot budget
    pauses: tuple = ()    # per-node pause budget
    skews: tuple = ()     # per-node skew-jump budget
    rebalance: bool = False
    depth: int = 10
    convergence: bool = True

    def budget(self, values: tuple, default: int = 0) -> list[int]:
        return [values[i] if i < len(values) else default
                for i in range(len(self.nodes))]

    @property
    def effective_lease_ms(self) -> int:
        return max(int(self.lease_ms), 3 * int(self.interval_ms))

    @property
    def max_skew_spread_ms(self) -> int:
        return max((s.skew_ms for s in self.nodes), default=0)


class Node:
    def __init__(self, model: "Model", idx: int, spec: NodeSpec):
        self.model = model
        self.idx = idx
        self.spec = spec
        self.alive = True
        self.paused = False
        ctx = ModelCtx()
        ctx.server_id = idx + 1
        ctx.host = "model"
        ctx.port = 7000 + idx
        ctx.boot_epoch = idx + 1
        ctx.config = model.config
        ctx.persistence = model.persistence
        ctx.running_queries = {}
        ctx.store = _ModelStore()
        placer = Placer(
            ctx,
            interval_ms=model.scenario.interval_ms if spec.armed else None,
            lease_ms=model.scenario.lease_ms)
        placer.resume_fn = self._resume
        ctx.placer = placer
        ctx.heartbeat_lease_ms = placer.lease_ms
        self.ctx = ctx
        self.name = scheduler.node_name(ctx)

    @property
    def armed(self) -> bool:
        return self.ctx.placer.armed

    @property
    def running(self) -> dict:
        return self.ctx.running_queries

    def _resume(self, info) -> None:
        self.ctx.running_queries[info.query_id] = ModelTask(info.query_id)


class Model:
    """Mutable model state + the action interface the explorer drives.
    Exploration mutates in place; ``snapshot``/``restore`` back out."""

    def __init__(self, scenario: Scenario):
        self.scenario = scenario
        self.kv = MetaKV()
        self.config = VersionedConfigStore(self.kv)
        self.clock = VirtualClock()
        self.persistence = ModelPersistence()
        self.nodes = [Node(self, i, s) for i, s in enumerate(scenario.nodes)]
        self.name_to_idx = {n.name: n.idx for n in self.nodes}
        self.crashes = scenario.budget(scenario.crashes)
        self.reboots = scenario.budget(scenario.reboots)
        self.pauses = scenario.budget(scenario.pauses)
        self.skews = scenario.budget(scenario.skews)
        self.advances_left = scenario.advances
        # qid -> (writer idx, true ms of the record's last write):
        # ground truth for the seizure invariant, independent of the
        # (possibly skewed) hb_ms the record itself carries
        self.truth: dict[str, tuple[int, int]] = {}
        self._build()

    # ---- construction ------------------------------------------------------

    def _build(self) -> None:
        with self.engaged():
            for spec in self.scenario.queries:
                self.persistence.add(QueryInfo(
                    query_id=spec.qid, sql="model", created_time_ms=0,
                    status=spec.status))
                if spec.offered_to is not None:
                    src = self.nodes[spec.src]
                    target = self.nodes[spec.offered_to]
                    with self.acting(src):
                        offer = json.dumps(
                            {"node": target.name, "epoch": 0,
                             "hb_ms": self.clock.read(),
                             "state": "offered",
                             "src": src.name}).encode()
                        self.config.put(SCHED_PREFIX + spec.qid, offer)
                    self.truth[spec.qid] = (src.idx, self.clock.true_ms)
                elif spec.owner is not None:
                    owner = self.nodes[spec.owner]
                    with self.acting(owner):
                        scheduler.record_assignment(owner.ctx, spec.qid)
                    self.truth[spec.qid] = (owner.idx, self.clock.true_ms)
                    if spec.status == TaskStatus.RUNNING:
                        owner.running[spec.qid] = ModelTask(spec.qid)

    # ---- clock plumbing ----------------------------------------------------

    @contextlib.contextmanager
    def engaged(self):
        """Patch the wall clock out of every module the protocol
        reads time through; restore on exit."""
        import time as _time

        saved_now = scheduler.now_ms
        saved_core = placer_core.time
        saved_score = placer_score.time
        shim = _TimeShim(self.clock, _time)
        scheduler.now_ms = self.clock.read
        placer_core.time = shim
        placer_score.time = shim
        try:
            yield self
        finally:
            scheduler.now_ms = saved_now
            placer_core.time = saved_core
            placer_score.time = saved_score

    @contextlib.contextmanager
    def acting(self, node: Node):
        prev = self.clock.active
        self.clock.active = node.idx
        try:
            yield node
        finally:
            self.clock.active = prev

    # ---- actions -----------------------------------------------------------

    def enabled_actions(self) -> list[tuple]:
        acts: list[tuple] = []
        sc = self.scenario
        for i, n in enumerate(self.nodes):
            if n.alive and not n.paused and n.armed:
                acts.append(("hb", i))
                acts.append(("adopt", i))
                if sc.rebalance:
                    acts.append(("pub", i))
                    acts.append(("reb", i))
            if n.alive and self.crashes[i] > 0:
                acts.append(("crash", i))
            if not n.alive and self.reboots[i] > 0:
                acts.append(("reboot", i))
            if n.alive and not n.paused and self.pauses[i] > 0:
                acts.append(("pause", i))
            if n.alive and n.paused:
                acts.append(("resume", i))
            if self.skews[i] > 0 and n.spec.skew_ms:
                acts.append(("skew", i))
        if self.advances_left > 0:
            acts.append(("advance",))
        return acts

    def execute(self, action: tuple) -> None:
        """Run one action against the live protocol code. The caller
        (explorer / invariants) diffs records around this."""
        kind = action[0]
        if kind == "advance":
            self.clock.true_ms += self.scenario.quantum_ms
            self.advances_left -= 1
            return
        i = action[1]
        n = self.nodes[i]
        if kind == "hb":
            with self.acting(n):
                n.ctx.placer._heartbeat_owned()
        elif kind == "adopt":
            with self.acting(n):
                n.ctx.placer._adopt_sweep()
        elif kind == "pub":
            with self.acting(n):
                self._publish(n)
        elif kind == "reb":
            with self.acting(n):
                n.ctx.placer._rebalance()
        elif kind == "crash":
            self.crashes[i] -= 1
            n.alive = False
            n.paused = False
            n.running.clear()
        elif kind == "reboot":
            self.reboots[i] -= 1
            n.alive = True
            n.ctx.boot_epoch = max(m.ctx.boot_epoch
                                   for m in self.nodes) + 1
            with self.acting(n):
                self._boot_adopt(n)
        elif kind == "pause":
            self.pauses[i] -= 1
            n.paused = True
        elif kind == "resume":
            n.paused = False
        elif kind == "skew":
            self.skews[i] = 0
            self.clock.skew[i] = n.spec.skew_ms
        else:  # pragma: no cover — explorer only emits the above
            raise ValueError(f"unknown action {action!r}")

    def _publish(self, n: Node) -> None:
        """Minimal cluster/nodes record: the fields rank_nodes and
        skip_reason read (the full node_record_fields shape needs the
        stats plane; ranking only consumes these axes)."""
        rec = {"node": n.name, "hb_ms": self.clock.read(),
               "running_queries": len(n.running),
               "shed_level": 0, "fenced": False, "health": {}}
        key = NODE_PREFIX + n.name
        value = json.dumps(rec).encode()
        for _ in range(4):
            cur = self.config.get(key)
            try:
                self.config.put(key, value, base_version=None
                                if cur is None else cur[0])
                return
            except VersionMismatch:  # pragma: no cover — atomic model
                continue

    def _boot_adopt(self, n: Node) -> None:
        """Mirror of handlers.resume_persisted's adoption sweep: the
        armed owner_live gate, then the real try_adopt CAS claim."""
        ctx = n.ctx
        for info in self.persistence.get_queries():
            if info.status not in (TaskStatus.RUNNING, TaskStatus.CREATED):
                continue
            if info.query_id in ctx.running_queries:
                continue
            if not scheduler.adoption_allowed(ctx, info.query_id):
                continue  # pragma: no cover — model flow is None
            if ctx.placer.armed:
                rec = scheduler.assignment(ctx, info.query_id)
                if (rec is not None
                        and rec.get("node") != scheduler.node_name(ctx)
                        and scheduler.owner_live(
                            rec, ctx.heartbeat_lease_ms)):
                    continue
            if not scheduler.try_adopt(ctx, info.query_id):
                continue
            n._resume(info)
            self.persistence.set_query_status(info.query_id,
                                              TaskStatus.RUNNING)

    # ---- record access -----------------------------------------------------

    def sched_records(self) -> dict[str, tuple[bytes, dict]]:
        """qid -> (raw value, parsed record) for every live
        scheduler/query key."""
        out: dict[str, tuple[bytes, dict]] = {}
        for key in self.kv.meta_list(self.config.PREFIX + SCHED_PREFIX):
            short = key[len(self.config.PREFIX):]
            cur = self.config.get(short)
            if cur is None:
                continue
            try:
                rec = json.loads(cur[1])
            except ValueError:
                rec = None
            out[short[len(SCHED_PREFIX):]] = (cur[1], rec)
        return out

    def update_truth(self, action: tuple,
                     pre: dict[str, tuple[bytes, dict]],
                     post: dict[str, tuple[bytes, dict]]) -> None:
        """After a node action that rewrote a record, the acting node
        is its writer at the current true time."""
        if action[0] in ("advance", "crash", "pause", "resume", "skew"):
            return
        actor = action[1]
        for qid, (raw, _rec) in post.items():
            if qid not in pre or pre[qid][0] != raw:
                self.truth[qid] = (actor, self.clock.true_ms)

    # ---- snapshot / restore ------------------------------------------------

    def snapshot(self) -> tuple:
        return (
            dict(self.kv.data),
            self.clock.true_ms,
            dict(self.clock.skew),
            self.advances_left,
            tuple(self.crashes), tuple(self.reboots),
            tuple(self.pauses), tuple(self.skews),
            tuple((n.alive, n.paused, n.ctx.boot_epoch,
                   tuple(sorted(n.running))) for n in self.nodes),
            self.persistence.statuses(),
            dict(self.truth),
        )

    def restore(self, snap: tuple) -> None:
        (data, true_ms, skew, advances, crashes, reboots, pauses,
         skews, node_states, statuses, truth) = snap
        self.kv.data = dict(data)
        self.clock.true_ms = true_ms
        self.clock.skew = dict(skew)
        self.advances_left = advances
        self.crashes = list(crashes)
        self.reboots = list(reboots)
        self.pauses = list(pauses)
        self.skews = list(skews)
        for n, (alive, paused, epoch, running) in zip(self.nodes,
                                                      node_states):
            n.alive = alive
            n.paused = paused
            n.ctx.boot_epoch = epoch
            n.ctx.running_queries.clear()
            for qid in running:
                n.ctx.running_queries[qid] = ModelTask(qid)
        for qid, status in statuses:
            self.persistence.set_query_status(qid, status)
        self.truth = dict(truth)

    # ---- canonical state key -----------------------------------------------

    def state_key(self) -> tuple:
        """Behavior-equivalence fingerprint: epochs rank-canonical,
        every timestamp an offset from virtual now (the protocol only
        reads epoch ORDER and stamp AGES), budgets included so a state
        with fewer crashes left is not conflated with a fresh one."""
        now = self.clock.true_ms
        epochs = {n.ctx.boot_epoch for n in self.nodes}
        records = []
        for key in self.kv.meta_list(self.config.PREFIX):
            short = key[len(self.config.PREFIX):]
            cur = self.config.get(short)
            if cur is None:
                records.append((short, None))
                continue
            try:
                rec = json.loads(cur[1])
            except ValueError:
                records.append((short, ("raw", cur[1])))
                continue
            if "epoch" in rec:
                epochs.add(int(rec.get("epoch", 0)))
            records.append((short, rec))
        rank = {e: i for i, e in enumerate(sorted(epochs))}
        canon = []
        for short, rec in records:
            if rec is None or not isinstance(rec, dict):
                canon.append((short, rec))
                continue
            canon.append((short, (
                self.name_to_idx.get(rec.get("node"), rec.get("node")),
                rank.get(int(rec.get("epoch", 0)))
                if "epoch" in rec else None,
                rec.get("state"),
                (int(rec["hb_ms"]) - BASE_MS - now)
                if "hb_ms" in rec else None,
                self.name_to_idx.get(rec.get("src"), rec.get("src")),
                rec.get("running_queries"),
            )))
        return (
            tuple(canon),
            tuple((n.alive, n.paused, rank[n.ctx.boot_epoch],
                   tuple(sorted(n.running))) for n in self.nodes),
            tuple(self.crashes), tuple(self.reboots),
            tuple(self.pauses), tuple(self.skews),
            self.advances_left,
            tuple(sorted(self.clock.skew.items())),
            self.persistence.statuses(),
            tuple(sorted((q, w, t - now)
                         for q, (w, t) in self.truth.items())),
        )

    # ---- independence (sleep sets) -----------------------------------------

    def independent(self, a: tuple, b: tuple) -> bool:
        """Conservative commutation test for sleep-set pruning. Only
        pairs whose record/clock/node footprints are provably disjoint
        commute; everything else is treated as dependent."""
        if a[0] == "advance" or b[0] == "advance":
            return False  # every stamp-reading action races the clock
        if len(a) < 2 or len(b) < 2 or a[1] == b[1]:
            return False  # same node: trivially dependent
        na, nb = self.nodes[a[1]], self.nodes[b[1]]
        # adopt/reb/reboot read (and may write) any query record;
        # crash/skew change inputs adopt reads (liveness, stamps)
        wide = ("adopt", "reb", "reboot", "crash", "skew")
        if a[0] in wide or b[0] in wide:
            return False
        # hb touches the acting node's own running-set records; pub
        # touches the acting node's own cluster record
        if a[0] in ("hb", "pub") and b[0] in ("hb", "pub"):
            if a[0] == "hb" and b[0] == "hb":
                return not (set(na.running) & set(nb.running))
            return True  # hb vs pub / pub vs pub: disjoint key spaces
        # pause/resume only flip the acting node's flags
        if a[0] in ("pause", "resume") or b[0] in ("pause", "resume"):
            return True
        return False

    # ---- convergence oracle ------------------------------------------------

    def stabilize(self) -> None:
        """Deterministic quiescence drive: resume the paused, lapse
        every stale lease, let every armed survivor heartbeat and
        sweep for three rounds. After this, ownership must have
        converged (invariants.check_convergence asserts it)."""
        for n in self.nodes:
            n.paused = False
        if not any(n.alive and n.armed for n in self.nodes):
            return
        lease = self.scenario.effective_lease_ms
        for _ in range(3):
            self.clock.true_ms += lease + self.scenario.quantum_ms
            for n in self.nodes:
                if n.alive and n.armed:
                    with self.acting(n):
                        n.ctx.placer._heartbeat_owned()
            for n in self.nodes:
                if n.alive and n.armed:
                    with self.acting(n):
                        n.ctx.placer._adopt_sweep()
        for n in self.nodes:
            if n.alive and n.armed:
                with self.acting(n):
                    n.ctx.placer._heartbeat_owned()


# ---- scenario registry ------------------------------------------------------

_R = TaskStatus.RUNNING
_C = TaskStatus.CREATED


def _scenarios() -> dict[str, Scenario]:
    out = [
        Scenario(
            name="kill-2",
            description="2 armed nodes, 1 query each; each node may "
                        "crash once and reboot once",
            nodes=(NodeSpec(), NodeSpec()),
            queries=(QuerySpec("q1", owner=0), QuerySpec("q2", owner=1)),
            crashes=(1, 1), reboots=(1, 1), advances=4, depth=11),
        Scenario(
            name="pause-2",
            description="2 armed nodes; a paused owner keeps running "
                        "its task through a lapsed lease (the zombie "
                        "window) and must self-fence on resume",
            nodes=(NodeSpec(), NodeSpec()),
            queries=(QuerySpec("q1", owner=0), QuerySpec("q2", owner=1)),
            pauses=(1, 1), advances=4, depth=11),
        Scenario(
            name="skew-2",
            description="2 armed nodes with a one-way clock jump on "
                        "each; a skewed reader must never seize a "
                        "lease that is fresh in true time",
            nodes=(NodeSpec(skew_ms=1000), NodeSpec(skew_ms=1000)),
            queries=(QuerySpec("q1", owner=0),),
            crashes=(1, 0), reboots=(1, 0), skews=(1, 1),
            advances=4, depth=10),
        Scenario(
            name="kill-3",
            description="3 armed nodes, 2 queries; one crash + reboot "
                        "and one pause across the cluster",
            nodes=(NodeSpec(), NodeSpec(), NodeSpec()),
            queries=(QuerySpec("q1", owner=0), QuerySpec("q2", owner=1)),
            crashes=(1, 0, 0), reboots=(1, 0, 0), pauses=(0, 1, 0),
            advances=3, depth=9),
        Scenario(
            name="mixed-2",
            description="armed node beside a disarmed (legacy-record) "
                        "node: the live sweep must never apply the "
                        "epoch rule to a legacy record",
            nodes=(NodeSpec(armed=False), NodeSpec()),
            queries=(QuerySpec("q1", owner=0), QuerySpec("q2", owner=1)),
            advances=4, depth=10),
        Scenario(
            name="clamp-2",
            description="lease configured below 3x interval: the "
                        "placer's clamp must keep a one-quantum-stale "
                        "owner safe from seizure",
            nodes=(NodeSpec(), NodeSpec()),
            queries=(QuerySpec("q1", owner=0),),
            interval_ms=2000, lease_ms=2000, quantum_ms=2000,
            crashes=(1, 0), reboots=(0, 0), advances=5, depth=10),
        Scenario(
            name="rebalance-2",
            description="3 queries on one node, none on the other: "
                        "publish + rebalance offers must converge to "
                        "single ownership, never two live owners",
            nodes=(NodeSpec(), NodeSpec()),
            queries=(QuerySpec("q1", owner=0), QuerySpec("q2", owner=0),
                     QuerySpec("q3", owner=0)),
            rebalance=True, advances=2, depth=7),
        Scenario(
            name="created-2",
            description="a CREATED query whose offered record's "
                        "target crashes before claiming: survivors "
                        "must rescue it once the offer lapses",
            nodes=(NodeSpec(), NodeSpec()),
            queries=(QuerySpec("q1", owner=None, status=_C,
                               offered_to=1, src=0),
                     QuerySpec("q2", owner=0)),
            crashes=(0, 1), reboots=(0, 0), advances=4, depth=9),
    ]
    return {s.name: s for s in out}


SCENARIOS: dict[str, Scenario] = _scenarios()

# the bounded set CI runs (acceptance: 2-node and 3-node kill/pause/
# skew models, plus the discipline scenarios the mutants need)
DEFAULT_SCENARIOS = ("kill-2", "pause-2", "skew-2", "kill-3",
                     "mixed-2", "clamp-2", "rebalance-2", "created-2")
