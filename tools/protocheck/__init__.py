"""protocheck: explicit-state model checking of the ownership protocol.

PR 13 machine-certified the lock layer (``tools/analyze`` lockorder/
atomicity); this package does the same for the DISTRIBUTED state
machine those locks protect: the query-ownership / heartbeat-lease /
boot-epoch protocol of ``server/scheduler.py`` + ``placer/core.py``,
and the epoch fence / promote rules of ``store/replica.py``.

The checker drives the REAL protocol functions — ``try_adopt``,
``try_adopt_live``, ``heartbeat_assignment``, ``offer_assignment``,
``owner_live``, the placer tick stages, ``FollowerService.Replicate``
and ``Promote`` — against an in-memory ``meta_cas`` config store under
a controlled scheduler: a virtual clock replaces the wall clock, every
placer stage / boot sweep / crash / pause / clock-skew step is one
atomic model action, and the explorer enumerates all interleavings of
those actions up to a bounded depth with visited-state dedup plus
sleep-set (DPOR-style) transition pruning.

Soundness notes (what a green run certifies):

* Actions are ATOMIC — one whole protocol function per step. Races
  *between* ticks (the distributed protocol) are exhaustively
  explored; races *inside* one function (CAS retry loops, the torn
  pack attach, the FAILED-status clobber) are thread-level and remain
  the domain of PR 13's lockorder/atomicity certification.
* Time is quantized (``Scenario.quantum_ms``) and horizon-bounded, so
  the state space is finite; state keys are translation-invariant in
  time and rank-canonical in epochs, so depth bounds cut nothing a
  shifted clock would have reached.
* Sleep-set pruning only skips a transition whose effect is provably
  identical to an already-explored one (conservative independence:
  disjoint record footprints); visited-state dedup re-explores a state
  only for actions not yet tried from it. Every reachable state is
  visited and every (state, action) post-condition is either executed
  or a commuted copy of an executed one.

The checker is itself mutation-gated: ``tools/protocheck/mutants.py``
mechanically reverts each PR 17 review fix and the gate requires a
counterexample trace for every mutant (see ``python -m tools.protocheck
--mutants``).
"""

from tools.protocheck.invariants import Violation  # noqa: F401
from tools.protocheck.model import SCENARIOS, Model, Scenario  # noqa: F401
from tools.protocheck.explore import (  # noqa: F401
    Counterexample,
    ExploreResult,
    explore,
    replay,
)
