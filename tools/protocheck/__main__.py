"""CLI: ``python -m tools.protocheck``.

Default run explores every registered scenario (ownership/failover
models + the replica epoch model) against the LIVE protocol code and
fails on any invariant violation. ``--mutants`` runs the mutation
gate: every mechanically reverted PR 17/PR 9 fix must yield a
counterexample, proving the checker can actually see the bugs those
fixes closed. The last counterexample is persisted to
``.protocheck-last.json``; ``--explain`` replays it as a per-step
record/owner timeline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

LAST_CE_PATH = ".protocheck-last.json"


def _save_ce(ce, repo: str) -> None:
    try:
        with open(os.path.join(repo, LAST_CE_PATH), "w",
                  encoding="utf-8") as f:
            json.dump(ce.to_json(), f, indent=1)
    except OSError:
        pass


def _fmt_result(r) -> str:
    mark = "ok " if r.ok else "FAIL"
    return (f"  {mark} {r.scenario:<12} states={r.states:<7} "
            f"transitions={r.transitions:<8} depth<={r.depth} "
            f"pruned(visited={r.pruned_visited} "
            f"sleep={r.pruned_sleep}) {r.elapsed_s:.1f}s")


def run_live(names: list[str], depth: int | None, out: list) -> bool:
    from tools.protocheck.explore import explore
    from tools.protocheck.model import SCENARIOS
    from tools.protocheck.replica_model import (ReplicaScenario,
                                                explore_replica)

    ok = True
    for name in names:
        if name == "replica-2":
            r = explore_replica(ReplicaScenario(), max_depth=depth)
        else:
            r = explore(SCENARIOS[name], max_depth=depth)
        out.append(r)
        print(_fmt_result(r))
        if not r.ok:
            ok = False
            ce = r.counterexample
            print(f"       counterexample [{ce.rule}]: {ce.message}")
            for i, a in enumerate(ce.trace):
                print(f"         {i + 1}. {tuple(a)}")
    return ok


def run_mutants(which: list[str] | None, out: list) -> bool:
    from tools.protocheck.explore import explore
    from tools.protocheck.model import SCENARIOS
    from tools.protocheck.mutants import BY_NAME, MUTANTS
    from tools.protocheck.replica_model import (ReplicaScenario,
                                                explore_replica)

    todo = MUTANTS if not which else [BY_NAME[n] for n in which]
    ok = True
    for m in todo:
        if m.kind == "replica":
            r = explore_replica(ReplicaScenario(), mutant=m)
        else:
            r = explore(SCENARIOS[m.scenario], mutant=m)
        out.append(r)
        if r.ok:
            ok = False
            print(f"  FAIL {m.name:<24} NOT CAUGHT "
                  f"(scenario {m.scenario}, states={r.states}, "
                  f"{r.elapsed_s:.1f}s) — reverting '{m.fix}' went "
                  f"unnoticed")
        else:
            ce = r.counterexample
            print(f"  ok   {m.name:<24} caught by {ce.rule} after "
                  f"{len(ce.trace)} steps ({r.elapsed_s:.1f}s)")
    return ok


def explain(repo: str) -> int:
    path = os.path.join(repo, LAST_CE_PATH)
    if not os.path.exists(path):
        print("no saved counterexample (.protocheck-last.json); run "
              "the checker first")
        return 2
    from tools.protocheck.explore import Counterexample
    with open(path, encoding="utf-8") as f:
        ce = Counterexample.from_json(json.load(f))
    mutant = None
    if ce.mutant:
        from tools.protocheck.mutants import BY_NAME
        mutant = BY_NAME[ce.mutant]
    print(f"scenario {ce.scenario}"
          + (f" under mutant {ce.mutant}" if ce.mutant else "")
          + f" — violates {ce.rule}"
          + (" (during stabilization)" if ce.stabilized else ""))
    print(f"  {ce.message}\n")
    if ce.scenario == "replica-2":
        _explain_replica(ce, mutant)
        return 0
    from tools.protocheck.explore import replay
    from tools.protocheck.model import SCENARIOS
    _vs, _keys, steps = replay(SCENARIOS[ce.scenario], ce.trace,
                               mutant=mutant,
                               stabilize=ce.stabilized, timeline=True)
    for i, st in enumerate(steps):
        print(f"step {i}: {st['action']}  (t={st['clock_ms']}ms)")
        for n in st["nodes"]:
            flags = []
            if not n["alive"]:
                flags.append("DOWN")
            if n["paused"]:
                flags.append("paused")
            if not n["armed"]:
                flags.append("disarmed")
            if n["skew_ms"]:
                flags.append(f"skew{n['skew_ms']:+d}ms")
            print(f"    {n['name']:<22} epoch={n['epoch']} "
                  f"running={n['running']}"
                  + (f"  [{' '.join(flags)}]" if flags else ""))
        for qid, rec in st["records"].items():
            if rec.get("raw"):
                print(f"    {qid}: <unparseable record>")
                continue
            bits = [f"{rec['state']} by {rec['node']}",
                    f"epoch={rec['epoch']}"]
            if "hb_age_ms" in rec:
                bits.append(f"hb_age={rec['hb_age_ms']}ms")
            if "src" in rec:
                bits.append(f"src={rec['src']}")
            print(f"    {qid}: " + "  ".join(bits))
        print()
    return 0


def _explain_replica(ce, mutant) -> None:
    from tools.protocheck.replica_model import replay_replica
    _vs, keys = replay_replica(ce.trace, mutant=mutant,
                               stabilize=ce.stabilized)
    actions = ["initial"] + [str(tuple(a)) for a in ce.trace]
    if ce.stabilized:
        actions.append("stabilize")
    for i, key in enumerate(keys):
        fstates, leaders = key[0], key[1]
        print(f"step {i}: {actions[i] if i < len(actions) else '?'}")
        for epoch, lid, isl, seq, _fp in fstates:
            role = "LEADER" if isl else f"follows {lid!r}"
            print(f"    epoch={epoch} applied={seq} {role}")
        if leaders:
            print(f"    promoted identities: {list(leaders)}")
        print()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.protocheck",
        description="exhaustive state-space check of the ownership/"
                    "failover and replica-epoch protocols")
    ap.add_argument("--scenario", action="append",
                    help="run only this scenario (repeatable)")
    ap.add_argument("--depth", type=int, default=None,
                    help="override the per-scenario depth bound")
    ap.add_argument("--mutants", action="store_true",
                    help="mutation gate: every reverted fix must "
                         "yield a counterexample")
    ap.add_argument("--mutant", action="append",
                    help="gate only this mutant (repeatable; implies "
                         "--mutants)")
    ap.add_argument("--explain", action="store_true",
                    help="replay the last saved counterexample as a "
                         "per-step timeline")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable results on stdout")
    ap.add_argument("--repo", default=".",
                    help="repo root (for the saved counterexample)")
    args = ap.parse_args(argv)

    if args.explain:
        return explain(args.repo)

    from tools.protocheck.model import SCENARIOS

    names = args.scenario or (list(SCENARIOS) + ["replica-2"])
    for n in names:
        if n not in SCENARIOS and n != "replica-2":
            ap.error(f"unknown scenario {n!r} (have: "
                     f"{', '.join(list(SCENARIOS) + ['replica-2'])})")

    t0 = time.monotonic()
    results: list = []
    ok = True
    if args.mutants or args.mutant:
        print("mutation gate (each reverted fix must be caught):")
        ok = run_mutants(args.mutant, results) and ok
    else:
        print("live-tree exploration:")
        ok = run_live(names, args.depth, results) and ok

    # persist the most interesting counterexample for --explain:
    # a live-tree violation beats a mutant-gate one
    last_ce = None
    for r in results:
        if r.counterexample is not None:
            if last_ce is None or r.counterexample.mutant is None:
                last_ce = r.counterexample
    if last_ce is not None:
        _save_ce(last_ce, args.repo)
        print(f"\nlast counterexample saved to {LAST_CE_PATH}; "
              f"run with --explain for the timeline")

    elapsed = time.monotonic() - t0
    if args.json:
        print(json.dumps({
            "ok": ok, "elapsed_s": round(elapsed, 2),
            "results": [{
                "scenario": r.scenario, "states": r.states,
                "transitions": r.transitions, "depth": r.depth,
                "elapsed_s": round(r.elapsed_s, 2),
                "mutant": (r.counterexample.mutant
                           if r.counterexample else None),
                "violation": (r.counterexample.rule
                              if r.counterexample else None),
            } for r in results]}))
    else:
        verdict = "CERTIFIED" if ok else "VIOLATIONS FOUND"
        if args.mutants or args.mutant:
            verdict = ("MUTATION GATE PASSED" if ok
                       else "MUTATION GATE FAILED")
        print(f"\n{verdict} — {len(results)} run(s) in {elapsed:.1f}s")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
