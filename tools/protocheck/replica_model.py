"""Replica epoch/fence model: the real FollowerService under a model
operator.

Two live :class:`FollowerService` instances (the real class from
``store/replica.py``) run over minimal in-memory log stores. The model
operator plays every leader/promoter the protocol can see:

``("promote", i)``        promote follower ``i`` at ``max epoch + 1``
                          (what ``promote_best`` computes)
``("promote-dup", i)``    promote at the CURRENT max epoch — the
                          dueling-promotion race (legal only while
                          ``i``'s own epoch is behind)
``("promote-stale", i)``  promote at an epoch <= follower ``i``'s own:
                          must be a clean refusal
``("replicate", l, i)``   leader identity ``l`` sends one in-order
                          OP_META_PUT entry to follower ``i`` at the
                          leader's promotion epoch
``("seal", l, i)``        same, zero entries — a pure bind/fence probe

Invariants (the PR 9/17 epoch discipline):

* ``r-epoch-monotone`` — a follower's epoch never decreases;
* ``r-fenced-lands`` — a fenced (or refused) request leaves the
  follower's store byte-identical: a fenced writer never lands;
* ``r-stale-accept`` — a request from an epoch below the follower's
  is always fenced/refused;
* ``r-promote-guard`` — ``Promote`` only returns ok for an epoch
  strictly above the follower's;
* ``r-duel`` (convergence) — after every leader contacts every
  follower, at most one follower leads at the max epoch.

Exploration: plain DFS with visited-state dedup (the space is small —
no clocks, no leases); same counterexample/replay contract as the
ownership model.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from tools.protocheck.explore import Counterexample, ExploreResult
from tools.protocheck.invariants import Violation
from tools.protocheck.model import quiet_protocol_logs


class _Abort(Exception):
    def __init__(self, code, msg):
        super().__init__(f"{code}: {msg}")
        self.code = code


class _GrpcCtx:
    """Just enough grpc.ServicerContext for the follower methods."""

    def abort(self, code, msg):
        raise _Abort(code, msg)


class MiniLogStore:
    """The LogStore slice FollowerService + _apply touch, over plain
    dicts so snapshots are cheap copies."""

    def __init__(self):
        self.meta: dict[str, bytes] = {}
        self.logs: dict[int, list[bytes]] = {}

    # meta KV
    def meta_get(self, key):
        return self.meta.get(key)

    def meta_put(self, key, value):
        self.meta[key] = bytes(value)

    def meta_delete(self, key):
        self.meta.pop(key, None)

    # logs (lsn = count appended; trim never runs at model op counts)
    def log_exists(self, logid):
        return logid in self.logs

    def create_log(self, logid, attrs=None):
        self.logs.setdefault(logid, [])

    def remove_log(self, logid):
        self.logs.pop(logid, None)

    def tail_lsn(self, logid):
        return len(self.logs.get(logid, ()))

    def append(self, logid, payload):
        self.logs[logid].append(bytes(payload))
        return len(self.logs[logid])

    def trim(self, logid, upto):  # pragma: no cover — needs 512 ops
        raise NotImplementedError("model op budget keeps logs tiny")

    def snapshot(self):
        return (dict(self.meta),
                {k: list(v) for k, v in self.logs.items()})

    def restore(self, snap):
        meta, logs = snap
        self.meta = dict(meta)
        self.logs = {k: list(v) for k, v in logs.items()}

    def fingerprint(self):
        return (tuple(sorted(self.meta.items())),
                tuple((k, tuple(v))
                      for k, v in sorted(self.logs.items())))


@dataclass
class ReplicaScenario:
    name: str = "replica-2"
    description: str = ("2 followers; promotions (fresh, dueling, "
                        "stale) and in-order replication from every "
                        "promoted leader identity")
    followers: int = 2
    promotes: int = 3   # total successful-promotion budget
    ops: int = 2        # total replicated-entry budget
    depth: int = 7
    convergence: bool = True


class ReplicaModel:
    def __init__(self, scenario: ReplicaScenario):
        from hstream_tpu.store.replica import FollowerService

        self.scenario = scenario
        self.stores = [MiniLogStore() for _ in range(scenario.followers)]
        self.followers = [
            FollowerService(s, node_id=f"r{i + 1}",
                            listen_addr=f"model:{9000 + i}")
            for i, s in enumerate(self.stores)]
        # leader identities: (node_id, epoch) of every successful
        # promotion; a demoted/stale identity keeps sending — that is
        # exactly the partitioned-leader case the fence exists for
        self.leaders: list[tuple[str, int]] = []
        self.promotes_left = scenario.promotes
        self.ops_left = scenario.ops
        self.seq = 0  # distinct meta payloads per replicated op

    # ---- actions -----------------------------------------------------------

    def _max_epoch(self) -> int:
        return max([f.epoch for f in self.followers]
                   + [e for _n, e in self.leaders] + [0])

    def enabled_actions(self) -> list[tuple]:
        acts: list[tuple] = []
        for i, f in enumerate(self.followers):
            if self.promotes_left > 0:
                acts.append(("promote", i))
                if f.epoch < self._max_epoch():
                    acts.append(("promote-dup", i))
            acts.append(("promote-stale", i))
        for li, (lid, epoch) in enumerate(self.leaders):
            for i in range(len(self.followers)):
                acts.append(("seal", li, i))
                if self.ops_left > 0:
                    acts.append(("replicate", li, i))
        return acts

    def execute(self, action: tuple) -> list[Violation]:
        from hstream_tpu.proto import api_pb2 as pb

        kind = action[0]
        out: list[Violation] = []
        if kind.startswith("promote"):
            i = action[1]
            f = self.followers[i]
            pre_epoch = f.epoch
            pre_fp = self.stores[i].fingerprint()
            if kind == "promote":
                epoch = self._max_epoch() + 1
            elif kind == "promote-dup":
                epoch = self._max_epoch()
            else:
                epoch = pre_epoch
            req = pb.PromoteRequest(epoch=epoch,
                                    leader_addr=f"sql:{9100 + i}",
                                    promoted_by="protocheck")
            try:
                resp = f.Promote(req, _GrpcCtx())
                ok = bool(resp.ok)
            except _Abort:
                ok = False
            if ok:
                if epoch <= pre_epoch:
                    out.append(Violation(
                        "r-promote-guard",
                        f"Promote of {f.node_id} at epoch {epoch} "
                        f"succeeded although its epoch was already "
                        f"{pre_epoch}",
                        {"node": f.node_id, "epoch": epoch}))
                self.leaders.append((f.node_id, epoch))
                if kind != "promote-stale":
                    self.promotes_left -= 1
            elif epoch > pre_epoch:  # pragma: no cover — live refuses
                # only stale/dup epochs
                out.append(Violation(
                    "r-promote-guard",
                    f"Promote of {f.node_id} at fresh epoch {epoch} "
                    f"was refused (follower at {pre_epoch})",
                    {"node": f.node_id}))
            out += self._post_checks(i, pre_epoch, pre_fp,
                                     changed_ok=ok)
            return out

        _kind, li, i = action
        lid, epoch = self.leaders[li]
        f = self.followers[i]
        pre_epoch = f.epoch
        pre_fp = self.stores[i].fingerprint()
        req = pb.ReplicateRequest(epoch=epoch, leader_id=lid,
                                  leader_hint=f"sql:{lid}")
        if kind == "replicate":
            self.seq += 1
            req.entries.append(pb.LogEntry(
                op=pb.OP_META_PUT, seq=f.applied_seq + 1,
                meta_key="model/cell",
                meta_value=f"{lid}@{epoch}#{self.seq}".encode()))
        fenced = None
        try:
            resp = f.Replicate(req, _GrpcCtx())
            fenced = bool(resp.fenced)
        except _Abort:
            fenced = None  # refused outright; must not have landed
        if kind == "replicate" and fenced is False:
            self.ops_left -= 1
        if fenced is not False \
                and self.stores[i].fingerprint() != pre_fp:
            out.append(Violation(
                "r-fenced-lands",
                f"{kind} from {lid}@{epoch} to {f.node_id} was "
                f"{'fenced' if fenced else 'refused'} but changed "
                f"the follower's store — a fenced writer landed",
                {"node": f.node_id, "leader": lid, "epoch": epoch}))
        if epoch < pre_epoch and fenced is False:
            out.append(Violation(
                "r-stale-accept",
                f"{f.node_id} (epoch {pre_epoch}) accepted {kind} "
                f"from stale leader {lid}@{epoch}",
                {"node": f.node_id, "leader": lid, "epoch": epoch}))
        out += self._post_checks(i, pre_epoch, pre_fp,
                                 changed_ok=fenced is False)
        return out

    def _post_checks(self, i: int, pre_epoch: int, pre_fp,
                     changed_ok: bool) -> list[Violation]:
        out = []
        f = self.followers[i]
        if f.epoch < pre_epoch:
            out.append(Violation(
                "r-epoch-monotone",
                f"{f.node_id} epoch went BACKWARDS: {pre_epoch} -> "
                f"{f.epoch}",
                {"node": f.node_id, "pre": pre_epoch,
                 "post": f.epoch}))
        if not changed_ok and self.stores[i].fingerprint() != pre_fp \
                and f.epoch == pre_epoch:
            out.append(Violation(
                "r-fenced-lands",
                f"a refused request still changed {f.node_id}'s "
                f"store", {"node": f.node_id}))
        return out

    # ---- convergence -------------------------------------------------------

    def stabilize(self) -> list[Violation]:
        """Every leader identity contacts every follower twice (a
        seal round-trip resolves duels deterministically); then at
        most one follower may lead at the max epoch."""
        from hstream_tpu.proto import api_pb2 as pb

        out: list[Violation] = []
        for _round in range(2):
            for lid, epoch in sorted(self.leaders):
                for i, f in enumerate(self.followers):
                    if f.node_id == lid and f.epoch == epoch \
                            and f.is_leader:
                        continue  # a leader does not follow itself
                    pre_epoch = f.epoch
                    pre_fp = self.stores[i].fingerprint()
                    req = pb.ReplicateRequest(epoch=epoch,
                                              leader_id=lid)
                    try:
                        resp = f.Replicate(req, _GrpcCtx())
                        fenced = bool(resp.fenced)
                    except _Abort:
                        fenced = None
                    if fenced is not False \
                            and self.stores[i].fingerprint() != pre_fp:
                        out.append(Violation(
                            "r-fenced-lands",
                            f"stabilization seal from {lid}@{epoch} "
                            f"was fenced but landed on {f.node_id}",
                            {"node": f.node_id}))
                    if f.epoch < pre_epoch:
                        out.append(Violation(
                            "r-epoch-monotone",
                            f"{f.node_id} epoch went backwards "
                            f"during stabilization",
                            {"node": f.node_id}))
        top = max((f.epoch for f in self.followers), default=0)
        chiefs = [f.node_id for f in self.followers
                  if f.is_leader and f.epoch == top]
        if len(chiefs) > 1:
            out.append(Violation(
                "r-duel",
                f"two leaders at epoch {top} after full contact: "
                f"{chiefs} — dueling promotions never resolved",
                {"epoch": top, "leaders": chiefs}))
        return out

    # ---- snapshot / state key ----------------------------------------------

    def snapshot(self) -> tuple:
        return (
            tuple(s.snapshot() for s in self.stores),
            tuple((f._epoch, f._leader_id, f._leader_hint,
                   f._is_leader, f._broken, f._ops_since_trim)
                  for f in self.followers),
            tuple(self.leaders), self.promotes_left, self.ops_left,
            self.seq)

    def restore(self, snap: tuple) -> None:
        stores, fstates, leaders, promotes, ops, seq = snap
        for s, ss in zip(self.stores, stores):
            s.restore(ss)
        for f, (ep, lid, hint, isl, broken, ops_t) in zip(
                self.followers, fstates):
            f._epoch = ep
            f._leader_id = lid
            f._leader_hint = hint
            f._is_leader = isl
            f._broken = broken
            f._ops_since_trim = ops_t
        self.leaders = list(leaders)
        self.promotes_left = promotes
        self.ops_left = ops
        self.seq = seq

    def state_key(self) -> tuple:
        return (
            tuple((f._epoch, f._leader_id, f._is_leader,
                   f.applied_seq, self.stores[i].fingerprint())
                  for i, f in enumerate(self.followers)),
            tuple(self.leaders), self.promotes_left, self.ops_left)


def explore_replica(scenario: ReplicaScenario | None = None, *,
                    mutant=None, max_depth: int | None = None
                    ) -> ExploreResult:
    """Bounded DFS with visited-state dedup over the replica model."""
    sc = scenario or ReplicaScenario()
    depth_bound = sc.depth if max_depth is None else max_depth
    res = ExploreResult(scenario=sc.name, depth=depth_bound)
    t0 = time.monotonic()
    import contextlib as _ctx
    patch = mutant.patch() if mutant is not None else _ctx.nullcontext()
    trace: list[tuple] = []
    # canonical state -> largest remaining depth it was explored with;
    # a revisit with no more budget left is fully covered (this also
    # absorbs no-op self-loops like refused stale promotions)
    visited: dict[tuple, int] = {}
    conv_checked: set[tuple] = set()

    class _Hit(Exception):
        def __init__(self, v, stabilized):
            self.v = v
            self.stabilized = stabilized

    with quiet_protocol_logs(), patch:
        model = ReplicaModel(sc)

        def conv_check(key):
            if not sc.convergence or key in conv_checked:
                return
            conv_checked.add(key)
            snap = model.snapshot()
            try:
                vs = model.stabilize()
            finally:
                model.restore(snap)
            if vs:
                raise _Hit(vs[0], True)

        def dfs(depth):
            rem = depth_bound - depth
            if rem <= 0:
                return
            key = model.state_key()
            if visited.get(key, -1) >= rem:
                res.pruned_visited += 1
                return
            visited[key] = rem
            for a in model.enabled_actions():
                snap = model.snapshot()
                trace.append(a)
                vs = model.execute(a)
                res.transitions += 1
                if vs:
                    raise _Hit(vs[0], False)
                conv_check(model.state_key())
                dfs(depth + 1)
                model.restore(snap)
                trace.pop()

        try:
            conv_check(model.state_key())
            dfs(0)
        except _Hit as h:
            res.counterexample = Counterexample(
                scenario=sc.name, rule=h.v.rule, message=h.v.message,
                trace=list(trace), stabilized=h.stabilized,
                details=h.v.details,
                mutant=mutant.name if mutant is not None else None)
    res.states = len(visited)
    res.elapsed_s = time.monotonic() - t0
    return res


def replay_replica(trace: list, *, mutant=None, stabilize: bool = False
                   ) -> tuple[list, list]:
    """Re-execute a replica counterexample schedule; returns the final
    step's violations and the per-step state keys."""
    import contextlib as _ctx
    patch = mutant.patch() if mutant is not None else _ctx.nullcontext()
    keys: list = []
    violations: list = []
    with quiet_protocol_logs(), patch:
        model = ReplicaModel(ReplicaScenario())
        keys.append(model.state_key())
        for a in trace:
            violations = model.execute(tuple(a))
            keys.append(model.state_key())
        if stabilize and not violations:
            violations = model.stabilize()
            keys.append(model.state_key())
    return violations, keys
