"""Bounded exhaustive exploration with DPOR-style pruning.

Depth-first search over the model's action interleavings:

* **visited-state dedup** — the canonical state key (epoch-ranked,
  time-translated) maps to the set of actions already executed from
  that state; a revisit only runs the residue, so every reachable
  state executes every enabled action exactly once across the run;
* **sleep sets** — an action independent of everything executed since
  it was last deferred is skipped (its effect is a commuted copy of an
  executed transition); independence is the conservative footprint
  test in ``Model.independent``;
* **convergence oracle** — every distinct state additionally runs the
  deterministic stabilization drive on a snapshot and asserts
  ``check_convergence``.

On violation the explorer stops with a :class:`Counterexample`: the
exact action schedule from the initial state, replayable (and
deterministic — ``replay`` re-executes it step by step, which is also
how ``tests/test_chaos.py`` turns traces into chaos schedules and how
``--explain`` renders the per-step record/owner timeline).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

from tools.protocheck import invariants
from tools.protocheck.model import Model, Scenario, quiet_protocol_logs


@dataclass
class Counterexample:
    scenario: str
    rule: str
    message: str
    trace: list
    stabilized: bool  # violation surfaced in the stabilization drive
    details: dict = field(default_factory=dict)
    mutant: str | None = None

    def to_json(self) -> dict:
        return {"scenario": self.scenario, "rule": self.rule,
                "message": self.message,
                "trace": [list(a) for a in self.trace],
                "stabilized": self.stabilized, "details": self.details,
                "mutant": self.mutant}

    @classmethod
    def from_json(cls, d: dict) -> "Counterexample":
        return cls(scenario=d["scenario"], rule=d["rule"],
                   message=d["message"],
                   trace=[tuple(a) for a in d["trace"]],
                   stabilized=bool(d.get("stabilized")),
                   details=d.get("details", {}),
                   mutant=d.get("mutant"))


@dataclass
class ExploreResult:
    scenario: str
    states: int = 0
    transitions: int = 0
    pruned_sleep: int = 0
    pruned_visited: int = 0
    depth: int = 0
    elapsed_s: float = 0.0
    counterexample: Counterexample | None = None

    @property
    def ok(self) -> bool:
        return self.counterexample is None


class _Found(Exception):
    def __init__(self, violation: invariants.Violation, trace: list,
                 stabilized: bool):
        super().__init__(violation.message)
        self.violation = violation
        self.trace = trace
        self.stabilized = stabilized


def explore(scenario: Scenario, *, mutant=None,
            max_depth: int | None = None,
            convergence: bool | None = None) -> ExploreResult:
    """Exhaustively explore one scenario; stop at the first violation.
    ``mutant`` is an entry from ``tools.protocheck.mutants`` whose
    patch is held for the whole run (including model construction)."""
    depth_bound = scenario.depth if max_depth is None else max_depth
    check_conv = (scenario.convergence if convergence is None
                  else convergence)
    res = ExploreResult(scenario=scenario.name, depth=depth_bound,
                        counterexample=None)
    t0 = time.monotonic()
    patch = mutant.patch() if mutant is not None \
        else contextlib.nullcontext()
    trace: list[tuple] = []
    # canonical state -> largest remaining depth it was explored with;
    # revisiting with less (or equal) budget adds nothing — this also
    # absorbs no-op self-loop transitions without burning depth
    visited: dict[tuple, int] = {}
    conv_checked: set[tuple] = set()

    with quiet_protocol_logs(), patch:
        model = Model(scenario)
        with model.engaged():
            def conv_check(key: tuple) -> None:
                if not check_conv or key in conv_checked:
                    return
                conv_checked.add(key)
                snap = model.snapshot()
                try:
                    model.stabilize()
                    vs = invariants.check_convergence(model)
                finally:
                    model.restore(snap)
                if vs:
                    raise _Found(vs[0], list(trace), True)

            def dfs(depth: int, sleep: frozenset) -> None:
                rem = depth_bound - depth
                if rem <= 0:
                    return
                key = model.state_key()
                if visited.get(key, -1) >= rem:
                    res.pruned_visited += 1
                    return
                visited[key] = rem
                executed_here: list[tuple] = []
                for a in model.enabled_actions():
                    if a in sleep:
                        res.pruned_sleep += 1
                        continue
                    snap = model.snapshot()
                    pre = model.sched_records()
                    model.execute(a)
                    post = model.sched_records()
                    trace.append(a)
                    vs = invariants.check_transition(model, a, pre,
                                                     post)
                    model.update_truth(a, pre, post)
                    vs += invariants.check_state(model)
                    res.transitions += 1
                    if vs:
                        raise _Found(vs[0], list(trace), False)
                    child_key = model.state_key()
                    conv_check(child_key)
                    child_sleep = frozenset(
                        b for b in set(sleep) | set(executed_here)
                        if model.independent(b, a))
                    dfs(depth + 1, child_sleep)
                    model.restore(snap)
                    trace.pop()
                    executed_here.append(a)

            try:
                vs = invariants.check_state(model)
                if vs:
                    raise _Found(vs[0], [], False)
                conv_check(model.state_key())
                dfs(0, frozenset())
            except _Found as f:
                res.counterexample = Counterexample(
                    scenario=scenario.name, rule=f.violation.rule,
                    message=f.violation.message, trace=f.trace,
                    stabilized=f.stabilized,
                    details=f.violation.details,
                    mutant=mutant.name if mutant is not None else None)
    res.states = len(visited)
    res.elapsed_s = time.monotonic() - t0
    return res


def replay(scenario: Scenario, trace: list, *, mutant=None,
           stabilize: bool = False, timeline: bool = False
           ) -> tuple[list, list, list]:
    """Re-execute a counterexample schedule step by step on a fresh
    model. Returns (violations, state_keys, timeline_steps) — the
    violations of the FINAL step (plus convergence when ``stabilize``),
    one canonical state key per step (replay-determinism witness), and
    the per-step record/owner timeline when requested."""
    patch = mutant.patch() if mutant is not None \
        else contextlib.nullcontext()
    violations: list = []
    keys: list = []
    steps: list = []
    with quiet_protocol_logs(), patch:
        model = Model(scenario)
        with model.engaged():
            violations = invariants.check_state(model)
            keys.append(model.state_key())
            if timeline:
                steps.append(_timeline_step(model, None))
            for a in trace:
                a = tuple(a)
                pre = model.sched_records()
                model.execute(a)
                post = model.sched_records()
                violations = invariants.check_transition(
                    model, a, pre, post)
                model.update_truth(a, pre, post)
                violations += invariants.check_state(model)
                keys.append(model.state_key())
                if timeline:
                    steps.append(_timeline_step(model, a))
            if stabilize and not violations:
                model.stabilize()
                violations = invariants.check_convergence(model)
                keys.append(model.state_key())
                if timeline:
                    steps.append(_timeline_step(model, ("stabilize",)))
    return violations, keys, steps


def render_action(action: tuple | None, model: Model | None = None
                  ) -> str:
    if action is None:
        return "initial"
    if len(action) == 1:
        return action[0]
    name = (model.nodes[action[1]].name if model is not None
            else f"node{action[1]}")
    return f"{action[0]}({name})"


def _timeline_step(model: Model, action: tuple | None) -> dict:
    records = {}
    for qid, (_raw, rec) in sorted(model.sched_records().items()):
        if not isinstance(rec, dict):
            records[qid] = {"raw": True}
            continue
        entry = {"node": rec.get("node"),
                 "state": rec.get("state", "owned"),
                 "epoch": rec.get("epoch")}
        if "hb_ms" in rec:
            entry["hb_age_ms"] = (model.clock.true_ms
                                  - model.truth.get(qid, (0, 0))[1])
        if rec.get("src"):
            entry["src"] = rec.get("src")
        records[qid] = entry
    return {
        "action": render_action(action, model),
        "clock_ms": model.clock.true_ms,
        "nodes": [{"name": n.name, "alive": n.alive,
                   "paused": n.paused, "armed": n.armed,
                   "epoch": n.ctx.boot_epoch,
                   "skew_ms": model.clock.skew.get(n.idx, 0),
                   "running": sorted(n.running)}
                  for n in model.nodes],
        "records": records,
    }
