"""Regenerate ``hstream_tpu/proto/api_pb2.py`` without protoc.

The image carries neither ``protoc`` nor ``grpcio-tools``, but the
checked-in ``api_pb2.py`` is nothing more than a serialized
``FileDescriptorProto`` handed to the protobuf builder — so schema
evolution is a descriptor-level edit: parse the current blob, apply the
declarative edits below (idempotently — a field/message/method that
already exists is skipped), serialize, and rewrite the module.

Run from the repo root after editing the EDITS tables::

    python -m tools.protopatch          # rewrites api_pb2.py in place
    python -m tools.protopatch --check  # exit 1 if edits are unapplied

Keep ``proto/api.proto`` (the human-readable source of truth) in sync
by hand; CI imports the module and the dynamic rpc glue builds stubs
straight off the descriptor, so a drifted blob fails loudly.
"""

from __future__ import annotations

import argparse
import os
import sys

from google.protobuf import descriptor_pb2 as dpb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB2 = os.path.join(REPO, "hstream_tpu", "proto", "api_pb2.py")

T = dpb.FieldDescriptorProto

# message -> [(name, number, type[, label[, type_name]])] appended if
# absent (proto3 singular unless label says otherwise; type_name names
# the message type for TYPE_MESSAGE fields, package-qualified)
NEW_FIELDS = {
    "AppendRequest": [
        # idempotent producers (ISSUE 9): a client that stamps a
        # monotone (producer_id, seq) on its appends can retry across
        # leader failover — the server answers a remembered duplicate
        # with the ORIGINAL record ids instead of re-appending
        ("producer_id", 3, T.TYPE_STRING),
        ("producer_seq", 4, T.TYPE_UINT64),
    ],
    "AppendResponse": [
        # True when the append was answered from the dedup window (the
        # record_ids are the original append's)
        ("duplicate", 3, T.TYPE_BOOL),
    ],
    "LogEntry": [
        # idempotent appends: the producer stamp rides the replicated
        # entry itself, so every replica derives the SAME dedup window
        # from the op-log — a retry that straddles a promotion is
        # deduplicated by the new leader without any extra round trip
        ("producer_id", 13, T.TYPE_STRING),
        ("producer_seq", 14, T.TYPE_UINT64),
    ],
    "ReplicateRequest": [
        # epoch fencing: a stale leader's stream is rejected by epoch
        ("epoch", 3, T.TYPE_UINT64),
        # where clients should send traffic while this leader holds
        # the epoch (followers persist it and serve it as the hint)
        ("leader_hint", 4, T.TYPE_STRING),
    ],
    "ReplicateResponse": [
        ("epoch", 2, T.TYPE_UINT64),
        # fenced=True: the receiver holds a HIGHER epoch; the sender
        # must stop acting as leader (split-brain guard)
        ("fenced", 3, T.TYPE_BOOL),
        ("leader_hint", 4, T.TYPE_STRING),
    ],
    "ReplicaInfoResponse": [
        ("epoch", 4, T.TYPE_UINT64),
        ("leader_hint", 5, T.TYPE_STRING),
    ],
}

# new top-level messages: name -> [(field, number, type, ...)]
NEW_MESSAGES = {
    # Wire-speed ingest (ISSUE 12): each block is one FRAMED columnar
    # micro-batch (common/colframe.py) — the exact staging layout the
    # encode workers consume; the server bounds-checks and hands off,
    # no per-record protobuf parse/serialize on the append path.
    "AppendColumnarRequest": [
        ("stream_name", 1, T.TYPE_STRING),
        ("blocks", 2, T.TYPE_BYTES, T.LABEL_REPEATED),
    ],
    "AppendColumnarResponse": [
        ("stream_name", 1, T.TYPE_STRING),
        # one record id per block, in submission order
        ("record_ids", 2, T.TYPE_MESSAGE, T.LABEL_REPEATED,
         ".hstream.tpu.RecordId"),
        ("rows", 3, T.TYPE_UINT64),
    ],
    "PromoteRequest": [
        ("epoch", 1, T.TYPE_UINT64),
        ("leader_addr", 2, T.TYPE_STRING),
        ("promoted_by", 3, T.TYPE_STRING),
    ],
    "PromoteResponse": [
        ("ok", 1, T.TYPE_BOOL),
        ("epoch", 2, T.TYPE_UINT64),
        ("applied_seq", 3, T.TYPE_UINT64),
        ("node_id", 4, T.TYPE_STRING),
    ],
    # Cluster stats federation (ISSUE 15): every node folds its stats
    # holder into one NodeStatsReport — structured scalars for the
    # load axes the placer sorts on, plus the full per-stream rate
    # ladders / per-query health as a JSON detail blob (the admin
    # merge re-parses it; a schema per ladder level would freeze the
    # family table into the wire format)
    "ClusterStatsRequest": [],
    "NodeStatsReport": [
        ("node", 1, T.TYPE_STRING),
        ("role", 2, T.TYPE_STRING),
        ("ts_ms", 3, T.TYPE_INT64),
        ("rss_bytes", 4, T.TYPE_UINT64),
        ("running_queries", 5, T.TYPE_UINT32),
        ("append_inflight", 6, T.TYPE_UINT64),
        ("report", 7, T.TYPE_STRING),
    ],
    "ClusterStatsResponse": [
        ("reports", 1, T.TYPE_MESSAGE, T.LABEL_REPEATED,
         ".hstream.tpu.NodeStatsReport"),
    ],
}

# service -> [(method, input msg, output msg[, client_streaming])]
NEW_METHODS = {
    "HStreamApi": [
        # Wire-speed ingest (ISSUE 12): unary for one-shot producers,
        # client-streaming so N micro-batches amortize ONE RPC (the
        # per-call gRPC overhead co-located producers were paying)
        ("AppendColumnar", "AppendColumnarRequest",
         "AppendColumnarResponse"),
        ("AppendColumnarStream", "AppendColumnarRequest",
         "AppendColumnarResponse", True),
        # federation: a full server answers with its node load report
        ("ClusterStats", "ClusterStatsRequest", "ClusterStatsResponse"),
    ],
    "StoreReplica": [
        ("Promote", "PromoteRequest", "PromoteResponse"),
        # the same verb on the replica face, so a BARE follower
        # process (no HStreamApi) still reports into the merged table
        ("ClusterStats", "ClusterStatsRequest", "ClusterStatsResponse"),
    ],
}

PKG = ".hstream.tpu."


def _load_blob() -> bytes:
    sys.path.insert(0, REPO)
    from hstream_tpu.proto import api_pb2

    return api_pb2.DESCRIPTOR.serialized_pb


def patch(blob: bytes) -> tuple[bytes, int]:
    """Apply the edit tables; returns (new blob, number of edits)."""
    fdp = dpb.FileDescriptorProto()
    fdp.ParseFromString(blob)
    msgs = {m.name: m for m in fdp.message_type}
    edits = 0

    def add_field(msg, name, number, ftype,
                  label=T.LABEL_OPTIONAL, type_name=None):
        nonlocal edits
        if any(f.name == name for f in msg.field):
            return
        f = msg.field.add()
        f.name = name
        f.number = number
        f.type = ftype
        f.label = label
        if type_name is not None:
            f.type_name = type_name
        parts = name.split("_")
        f.json_name = parts[0] + "".join(p.title() for p in parts[1:])
        edits += 1

    for mname, fields in NEW_FIELDS.items():
        for spec in fields:
            add_field(msgs[mname], *spec)
    for mname, fields in NEW_MESSAGES.items():
        if mname in msgs:
            msg = msgs[mname]
        else:
            msg = fdp.message_type.add()
            msg.name = mname
            msgs[mname] = msg
            edits += 1
        for spec in fields:
            add_field(msg, *spec)
    for sname, methods in NEW_METHODS.items():
        svc = next(s for s in fdp.service if s.name == sname)
        for spec in methods:
            name, in_m, out_m = spec[:3]
            if any(m.name == name for m in svc.method):
                continue
            m = svc.method.add()
            m.name = name
            m.input_type = PKG + in_m
            m.output_type = PKG + out_m
            if len(spec) > 3 and spec[3]:
                m.client_streaming = True
            edits += 1
    return fdp.SerializeToString(), edits


TEMPLATE = '''\
# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# source: api.proto  (regenerated by tools/protopatch.py — the image
# has no protoc; schema evolution is a descriptor-level patch)
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


from google.protobuf import empty_pb2 as google_dot_protobuf_dot_empty__pb2
from google.protobuf import struct_pb2 as google_dot_protobuf_dot_struct__pb2


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'api_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
  _HSTREAMRECORDHEADER_ATTRIBUTESENTRY._options = None
  _HSTREAMRECORDHEADER_ATTRIBUTESENTRY._serialized_options = b'8\\001'
  _STREAMSTATS_COUNTERSENTRY._options = None
  _STREAMSTATS_COUNTERSENTRY._serialized_options = b'8\\001'
  _STREAMSTATS_RATESENTRY._options = None
  _STREAMSTATS_RATESENTRY._serialized_options = b'8\\001'
# @@protoc_insertion_point(module_scope)
'''


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("protopatch")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if the edit tables are not fully "
                         "applied to the checked-in blob")
    args = ap.parse_args(argv)
    blob = _load_blob()
    new_blob, edits = patch(blob)
    if args.check:
        if edits:
            print(f"api_pb2.py is missing {edits} descriptor edit(s); "
                  f"run: python -m tools.protopatch")
            return 1
        print("api_pb2.py descriptor is up to date")
        return 0
    if not edits:
        print("no edits to apply; api_pb2.py unchanged")
        return 0
    with open(PB2, "w", encoding="utf-8") as f:
        f.write(TEMPLATE.format(blob=new_blob))
    print(f"applied {edits} descriptor edit(s) -> {PB2}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
