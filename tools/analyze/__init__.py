"""hstream-analyze: repo-native AST static analysis (ISSUE 4).

The codebase is a concurrent system (locks, worker pools, credit
windows, replica ack tracking) layered over JAX-compiled hot paths, and
tests structurally cannot see interleavings or contract drift between
layers. This package restores a compile-time property per rule family,
in the spirit of RacerD (lock discipline from ownership inference) and
Engler et al.'s "bugs as deviant behavior" (rules inferred from the
tree's own majority idiom, violations flagged in the minority):

  locks        lock-guard / lock-order   guarded-attribute discipline
  lockorder    lockorder-cycle           whole-program lock-acquisition
                                         graph (with-nesting + cross-
                                         class call edges) is acyclic
  atomicity    atomicity-check-act       a guarded read's decision may
                                         not outlive its critical
                                         section when the branch acts
                                         on the same lock's state
  waitholding  wait-holding              no join/result/wait/queue
                                         blocking while holding an
                                         unrelated lock
  blocking     blocking-hot              no unbounded blocking in gRPC
                                         handlers, the Prometheus scrape
                                         path, or worker loops
  purity       jax-impure / jax-donated-reuse
                                         jit/shard_map'd fns stay pure;
                                         donated buffers are dead after
                                         the donating call
  dispatch     dispatch-budget / dispatch-sync
                                         `# contract: dispatches<=N
                                         fetches<=M` budgets hold
                                         statically; no bare device
                                         syncs in the kernel layer
  retrace      retrace-*                 jit wrappers are memoized,
                                         no traced branches, no float/
                                         unhashable statics, no raw
                                         len() compile-cache keys
  overflow     overflow-*                int32 narrows of time/seq
                                         values are guarded; no arith
                                         on pre-narrowed timestamps
  shardmap     shardmap-*                collectives stay inside mesh
                                         bodies, no host callbacks in
                                         shard_map, axis names spelled
  errcontract  err-http / err-retry-class / err-dead-retry
                                         gRPC status <-> HTTP mapping <->
                                         client retry classification
  lifecycle    resource-leak             threads/executors created by a
                                         class are joined/shut down on
                                         some close/stop path
  registry     registry-*                metric/event registries match
                                         call sites both directions
                                         (absorbs tools/metrics_lint.py)

Waivers: a finding on a line carrying (or immediately following a
comment-only line carrying) `# analyze: ok <rule>[,<rule>...]` — or a
bare `# analyze: ok` — is a reviewed, deliberate exception and is
suppressed. Baseline: `tools/analyze/baseline.json` holds grandfathered
findings keyed (rule, path, message) so CI fails only on regressions;
the tree currently carries an EMPTY baseline — keep it that way.

Run from the repo root (CI runs it in the fast tier-1 job):

    python -m tools.analyze [--only locks,registry] [--stats]
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# production code the passes scan; tests are excluded on purpose (they
# deliberately exercise error paths, fake blocking, etc.)
SCAN_ROOTS = ("hstream_tpu", "tools", "bench.py")
# generated protobuf output: no hand-written invariants to check
SKIP_PARTS = ("__pycache__", os.path.join("hstream_tpu", "proto"),
              os.path.join("tools", "analyze"))

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

_WAIVER_RE = re.compile(r"#\s*analyze:\s*ok\b\s*([\w\-, ]*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str      # repo-relative, forward slashes
    line: int
    message: str

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers drift, messages don't."""
        return (self.rule, self.path, self.message)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed source file shared by every pass: path, text, AST,
    and the per-line waiver map."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.rel)
        # line -> set of waived rules ("*" = all)
        self.waivers: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _WAIVER_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            waived = rules or {"*"}
            self.waivers.setdefault(i, set()).update(waived)
            if line.lstrip().startswith("#"):
                # comment-only line: the waiver covers the next line too
                self.waivers.setdefault(i + 1, set()).update(waived)

    def waived(self, line: int, rule: str) -> bool:
        w = self.waivers.get(line, ())
        return "*" in w or rule in w


def load_tree(repo: str = REPO) -> list[SourceFile]:
    files: list[SourceFile] = []
    for root in SCAN_ROOTS:
        p = os.path.join(repo, root)
        paths = [p] if os.path.isfile(p) else sorted(
            os.path.join(dirpath, f)
            for dirpath, _dirs, names in os.walk(p)
            for f in names if f.endswith(".py"))
        for path in paths:
            rel = os.path.relpath(path, repo)
            if any(part in rel for part in SKIP_PARTS):
                continue
            with open(path, encoding="utf-8") as f:
                files.append(SourceFile(path, rel, f.read()))
    return files


def all_passes() -> dict[str, object]:
    """name -> pass module, in canonical order."""
    from tools.analyze.passes import (
        atomicity,
        blocking,
        casdiscipline,
        dispatch,
        errcontract,
        lifecycle,
        lockorder,
        locks,
        overflow,
        purity,
        registry,
        retrace,
        shardmap,
        timeunit,
        waitholding,
    )

    return {m.NAME: m for m in
            (locks, lockorder, atomicity, waitholding, blocking,
             purity, dispatch, retrace, overflow, shardmap,
             errcontract, lifecycle, registry, casdiscipline,
             timeunit)}


def rule_passes() -> dict[str, str]:
    """rule id -> owning pass name (the --json `pass` field: CI
    annotators group/route findings by pass without re-deriving the
    mapping)."""
    return {rid: name for name, mod in all_passes().items()
            for rid in mod.RULES}


def load_baseline(path: str = BASELINE_PATH) -> set[tuple[str, str, str]]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    return {(e["rule"], e["path"], e["message"]) for e in entries}


def write_baseline(findings: list[Finding], path: str = BASELINE_PATH,
                   keep_rules: set[str] | None = None) -> None:
    """Write the baseline. `keep_rules`: rule ids whose EXISTING entries
    are preserved verbatim — used when only a subset of passes ran, so
    `--only X --write-baseline` cannot drop other passes' entries."""
    entries = [{"rule": f.rule, "path": f.path, "message": f.message}
               for f in findings]
    if keep_rules and os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            entries.extend(e for e in json.load(f)
                           if e["rule"] in keep_rules)
    entries.sort(key=lambda e: (e["rule"], e["path"], e["message"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=1)
        f.write("\n")


def run_passes(files: list[SourceFile], only: list[str] | None = None,
               repo: str = REPO) -> tuple[list[Finding], dict[str, str]]:
    """Run the (selected) passes; returns (unwaived findings, rule docs
    of every selected pass)."""
    passes = all_passes()
    if only:
        unknown = [n for n in only if n not in passes]
        if unknown:
            raise SystemExit(
                f"unknown pass(es) {unknown}; valid: {sorted(passes)}")
        passes = {n: passes[n] for n in only}
    by_rel = {f.rel: f for f in files}
    rules: dict[str, str] = {}
    out: list[Finding] = []
    # (path, line) -> rules actually suppressed there, for the
    # stale-waiver audit below
    suppressed: dict[tuple[str, int], set[str]] = {}
    for mod in passes.values():
        rules.update(mod.RULES)
        for finding in mod.run(files, repo):
            src = by_rel.get(finding.path)
            if src is not None and src.waived(finding.line, finding.rule):
                suppressed.setdefault(
                    (finding.path, finding.line), set()).add(finding.rule)
                continue
            out.append(finding)
    out.extend(_dead_waivers(files, set(rules), suppressed,
                             all_selected=only is None))
    rules[WAIVER_DEAD_RULE] = WAIVER_DEAD_DOC
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out, rules


WAIVER_DEAD_RULE = "waiver-dead"
WAIVER_DEAD_DOC = (
    "an `# analyze: ok` waiver that suppressed nothing in this run — "
    "the code it excused was fixed or moved, and a stale waiver is a "
    "standing license for the next regression at that site; delete "
    "the comment (waiver-dead findings cannot themselves be waived)")


def _dead_waivers(files: list[SourceFile], selected_rules: set[str],
                  suppressed: dict[tuple[str, int], set[str]],
                  all_selected: bool) -> list[Finding]:
    """The stale-waiver audit: every waiver comment must still suppress
    at least one finding of every rule it names. Scoped to the passes
    that ran — a waiver naming an unselected pass's rule is skipped,
    and BARE waivers (`# analyze: ok` with no rule list) are only
    auditable when every pass ran."""
    out: list[Finding] = []
    for src in files:
        for i, line in enumerate(src.lines, start=1):
            m = _WAIVER_RE.search(line)
            if not m:
                continue
            named = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            # a comment-only waiver line covers the next line too
            covered = ({i, i + 1} if line.lstrip().startswith("#")
                       else {i})
            hits: set[str] = set()
            for ln in covered:
                hits |= suppressed.get((src.rel, ln), set())
            if not named:
                if all_selected and not hits:
                    out.append(Finding(
                        WAIVER_DEAD_RULE, src.rel, i,
                        "bare waiver suppresses nothing — delete it"))
                continue
            for rule in sorted(named & selected_rules):
                if rule not in hits:
                    out.append(Finding(
                        WAIVER_DEAD_RULE, src.rel, i,
                        f"waiver for {rule} suppresses nothing — the "
                        f"excused finding is gone; delete the waiver"))
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        "python -m tools.analyze",
        description="repo-native static analysis (see tools/analyze)")
    ap.add_argument("--only", default=None,
                    help="comma-separated pass names "
                         "(locks,lockorder,atomicity,waitholding,"
                         "blocking,purity,dispatch,retrace,overflow,"
                         "shardmap,errcontract,lifecycle,registry,"
                         "casdiscipline,timeunit)")
    ap.add_argument("--stats", action="store_true",
                    help="emit per-rule finding counts (incl. baselined)")
    ap.add_argument("--json", action="store_true",
                    help="emit NEW findings as one JSON array of "
                         "{rule,path,line,message} records (CI "
                         "annotation tooling); exit code unchanged")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file (default tools/analyze/"
                         "baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into "
                         "the baseline file")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id + doc and exit")
    ap.add_argument("--repo", default=REPO, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    only = ([n.strip() for n in args.only.split(",") if n.strip()]
            if args.only else None)
    if args.list_rules:
        # rule docs come straight from the pass modules — nothing runs
        passes = all_passes()
        for name in (only or passes):
            if name not in passes:
                raise SystemExit(f"unknown pass {name!r}; "
                                 f"valid: {sorted(passes)}")
            for rid, doc in sorted(passes[name].RULES.items()):
                print(f"{rid}: {doc}")
        if only is None:
            # the framework-level waiver audit rides every full run
            print(f"{WAIVER_DEAD_RULE}: {WAIVER_DEAD_DOC}")
        return 0

    files = load_tree(args.repo)
    findings, rules = run_passes(files, only, args.repo)
    baseline = load_baseline(args.baseline)
    if args.write_baseline:
        # with --only, entries owned by the passes that did NOT run
        # survive the rewrite untouched
        ran = set(rules)
        all_rules: set[str] = set()
        for mod in all_passes().values():
            all_rules |= set(mod.RULES)
        write_baseline(findings, args.baseline,
                       keep_rules=all_rules - ran)
        print(f"analyze: baselined {len(findings)} finding(s)")
        return 0
    new = [f for f in findings if f.key() not in baseline]
    grandfathered = len(findings) - len(new)

    if args.json:
        # machine output only: one array of finding records, so CI
        # annotators never have to scrape the human report. Each
        # record carries its owning pass, and the array order is a
        # total order over the record fields — deterministic for CI
        # annotation diffing, so consumers stop re-sorting (ISSUE 14)
        owners = rule_passes()
        ordered = sorted(new, key=lambda f: (f.path, f.line, f.rule,
                                             f.message))
        print(json.dumps([{"pass": owners.get(f.rule, "?"),
                           "rule": f.rule, "path": f.path,
                           "line": f.line, "message": f.message}
                          for f in ordered]))
        return 1 if new else 0

    if args.stats:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print("analyze: per-rule finding counts "
              "(before baseline subtraction)")
        for rid in sorted(set(counts) | set(rules)):
            print(f"  {rid:>20}: {counts.get(rid, 0)}")

    if new:
        print(f"analyze: {len(new)} new finding(s)"
              + (f" ({grandfathered} baselined)" if grandfathered else ""))
        for f in new:
            print(f"  {f}")
        print("\nrule docs (fired rules):")
        for rid in sorted({f.rule for f in new}):
            print(f"  {rid}: {rules.get(rid, '?')}")
        print("\nwaive a reviewed exception with `# analyze: ok <rule>` "
              "on (or right above) the line;\ngrandfather pre-existing "
              "findings with `python -m tools.analyze --write-baseline`.")
        return 1
    npass = len(only) if only else len(all_passes())
    print(f"analyze: OK ({npass} pass(es), {len(files)} files"
          + (f", {grandfathered} baselined finding(s))" if grandfathered
             else ", no findings)"))
    return 0
