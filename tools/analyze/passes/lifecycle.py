"""Resource-lifecycle pass.

`resource-leak` — every `threading.Thread` / `ThreadPoolExecutor` /
`Timer` a class stores on `self` must be joined / shut down / cancelled
on some reachable teardown path. Daemon flags don't excuse the leak: a
daemon thread caught mid device-fetch at interpreter teardown aborts
the process (the repo learned this in QueryTask.run), and an
unreclaimed dispatcher keeps touching subsystems its owner already
released.

Detection is the tree's own idiom: an attribute is considered cleaned
up when some function in the same MODULE calls `.join()` /
`.shutdown()` / `.cancel()` on a RECEIVER that references the
attribute — directly (`self._pool.shutdown()`, `f._thread.join()`) or
through a one-step alias (`t = self._thread; t.join(...)`,
`for t in self._threads: t.join(...)`). Credit flows only from the
call's receiver, so an unrelated `os.path.join(...)` or `sep.join(...)`
in the same function cannot launder a leak. `run()` methods of Thread
subclasses are exempt as creators — a thread doesn't own itself.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import class_methods, dotted, walk_classes

NAME = "lifecycle"

RULES = {
    "resource-leak": (
        "Thread/ThreadPoolExecutor/Timer stored on self is never "
        "joined/shut down/cancelled by any function in the module — "
        "no reachable teardown path"),
}

_SPAWN_TYPES = {"Thread", "ThreadPoolExecutor", "ProcessPoolExecutor",
                "Timer"}
_CLEANUP_CALLS = {"join", "shutdown", "cancel"}
# receiver roots whose join/cancel are not resource teardown
_NOT_RESOURCE_ROOTS = {"os", "posixpath", "ntpath", "shutil", "str"}


def _spawn_attrs(cls: ast.ClassDef) -> dict[str, tuple[int, str]]:
    """self-attributes assigned a spawned resource anywhere in the
    class: attr -> (line, type name). List-of-threads assignments
    (comprehensions containing a Thread(...) call) count too."""
    out: dict[str, tuple[int, str]] = {}
    for method in class_methods(cls):
        for node in ast.walk(method):
            if not isinstance(node, ast.Assign):
                continue
            spawned = None
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call):
                    leaf = (dotted(sub.func) or "").split(".")[-1]
                    if leaf in _SPAWN_TYPES:
                        spawned = leaf
                        break
            if spawned is None:
                continue
            for t in node.targets:
                d = dotted(t)
                if d and d.startswith("self.") and d.count(".") == 1:
                    out.setdefault(d.split(".", 1)[1],
                                   (node.lineno, spawned))
    return out


def _attrs_in(node: ast.AST) -> set[str]:
    return {n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)}


def _cleaned_attrs(tree: ast.Module) -> set[str]:
    """Attribute names some function tears down: the receiver of a
    join/shutdown/cancel call references the attribute, directly or
    via a one-step alias (assignment / for-loop binding)."""
    cleaned: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # one-step aliases: name -> attrs referenced by its source expr
        alias: dict[str, set[str]] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                alias.setdefault(sub.targets[0].id,
                                 set()).update(_attrs_in(sub.value))
            elif isinstance(sub, ast.For) and isinstance(sub.target,
                                                         ast.Name):
                alias.setdefault(sub.target.id,
                                 set()).update(_attrs_in(sub.iter))
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _CLEANUP_CALLS):
                continue
            receiver = sub.func.value
            root = (dotted(receiver) or "").split(".")[0]
            if root in _NOT_RESOURCE_ROOTS:
                continue  # os.path.join & friends: not teardown
            cleaned |= _attrs_in(receiver)
            if root in alias:
                cleaned |= alias[root]
    return cleaned


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        cleaned = _cleaned_attrs(src.tree)
        for cls in walk_classes(src.tree):
            for attr, (line, typ) in sorted(_spawn_attrs(cls).items()):
                if attr not in cleaned:
                    out.append(Finding(
                        "resource-leak", src.rel, line,
                        f"{cls.name}.{attr} holds a {typ} that no "
                        f"function in this module joins/shuts down"))
    return out
