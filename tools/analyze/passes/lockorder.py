"""Whole-program lock-order pass (ISSUE 14).

The per-class `lock-order` rule catches a contradictory nesting only
when BOTH acquisitions sit in one class. The deadlocks that matter in
this tree span objects: the task holds `state_lock` and calls into the
supervisor (whose `_lock` guards the pending table), while some other
path takes the supervisor's lock first and reaches back into the task.
Neither class sees anything wrong alone — the cycle only exists in the
whole-program lock-acquisition graph. That graph is exactly what
GoodLock/lockdep maintain at runtime; this pass constructs it
statically, the RacerD way (compositional per-function summaries, then
a global check):

  nodes  lock classes — `ClassName.attr` for `self.<attr>` locks
         (condition variables collapse onto the lock they wrap, lock
         LISTS get one family node), `module:NAME` for module globals;
  edges  A -> B when some function acquires B while holding A, either
         by `with` nesting in one body or because a call made under A
         reaches a function whose transitive acquire summary contains
         B (call resolution through constructor-typed attributes, the
         unique program-wide attribute owner, and the `ctx` lexicon —
         see passes/conc.py).

`lockorder-cycle` flags every edge of a cycle with the full witness
ring in the message. A deliberate ordering gets a waiver on ANY edge
of the cycle (a reviewed rationale on one edge breaks the ring — the
pass suppresses the whole cycle, so the other edges don't nag).

Same-node edges are skipped (re-entrant RLocks and instance-to-
instance nesting of one lock class need runtime identity — the
locktrace witness owns that half).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.analyze import Finding
from tools.analyze.passes import conc

NAME = "lockorder"

RULES = {
    "lockorder-cycle": (
        "the whole-program lock-acquisition graph (with-nesting plus "
        "cross-class call edges) contains a cycle — a potential "
        "deadlock under the right interleaving; every edge of the "
        "cycle is flagged with the witness ring"),
}


@dataclass
class _Edge:
    src: str
    dst: str
    rel: str      # witness file
    line: int     # witness line
    where: str    # "Class.method" / "module.fn"
    how: str      # human description of the acquisition


class _FnWalk(ast.NodeVisitor):
    """Collect order edges from one function: nested `with` blocks and
    calls made while holding (callee summaries supply the inner
    locks). Nested defs are skipped — they run on other threads."""

    def __init__(self, src, fn, cls, prog):
        self.src = src
        self.fn = fn
        self.cls = cls
        self.prog = prog
        self.local_types = conc.fn_local_types(fn, cls, prog)
        self.held: list[str] = []
        self.edges: list[_Edge] = []
        self.where = (f"{cls.name}.{fn.name}" if cls is not None
                      else fn.name)
        for stmt in fn.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):  # noqa: N802 — own thread/scope
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _emit(self, dst: str, line: int, how: str) -> None:
        for held in self.held:
            if held != dst:
                self.edges.append(_Edge(
                    held, dst, self.src.rel, line, self.where, how))

    def visit_With(self, node: ast.With):  # noqa: N802
        taken: list[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            n = conc.with_lock_node(item.context_expr, self.cls,
                                    self.src.rel, self.prog,
                                    self.local_types)
            if n is not None:
                self._emit(n, node.lineno, f"with-nested acquire of "
                                           f"'{n}'")
                self.held.append(n)
                taken.append(n)
        for stmt in node.body:
            self.visit(stmt)
        for _ in taken:
            self.held.pop()

    def visit_Call(self, node: ast.Call):  # noqa: N802
        if self.held:
            tgt = conc.resolve_call(node, self.cls, self.src.rel,
                                    self.prog, self.local_types)
            if tgt is not None and id(tgt) != id(self.fn):
                inner = self.prog.acquires.get(id(tgt), set())
                name = (ast.unparse(node.func)
                        if hasattr(ast, "unparse") else "<call>")
                for dst in sorted(inner):
                    self._emit(dst, node.lineno,
                               f"call {name}() acquires '{dst}'")
        self.generic_visit(node)


def _collect_edges(files, prog) -> dict[tuple[str, str], _Edge]:
    edges: dict[tuple[str, str], _Edge] = {}
    for src in files:
        jobs: list[tuple[ast.FunctionDef, object]] = []
        for info in prog.classes:
            if info.rel != src.rel:
                continue
            jobs.extend((m, info) for m in info.methods.values())
        jobs.extend((f, None)
                    for f in prog.module_funcs.get(src.rel, {}).values())
        for fn, cls in jobs:
            for e in _FnWalk(src, fn, cls, prog).edges:
                # first witness wins; sorted job order keeps it stable
                edges.setdefault((e.src, e.dst), e)
    return edges


def _cycles(edges: dict[tuple[str, str], _Edge]
            ) -> list[list[_Edge]]:
    """Minimal witness cycles: for each edge a->b with a path b->..->a,
    the ring [a->b, b->.., ..->a] found by BFS. Each cycle is reported
    once, keyed by its node set."""
    adj: dict[str, list[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    for outs in adj.values():
        outs.sort()
    seen_rings: set[frozenset[str]] = set()
    out: list[list[_Edge]] = []
    for (a, b) in sorted(edges):
        # BFS from b back to a
        prev: dict[str, str] = {b: ""}
        queue = [b]
        while queue:
            cur = queue.pop(0)
            if cur == a:
                break
            for nxt in adj.get(cur, ()):
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        if a not in prev:
            continue
        # prev chains a <- ... <- b; rebuild the b -> .. -> a path
        chain = [a]
        cur = a
        while cur != b:
            cur = prev[cur]
            chain.append(cur)
        chain.reverse()          # b, ..., a
        ring = [(a, b)] + [(chain[i], chain[i + 1])
                           for i in range(len(chain) - 1)]
        key = frozenset(n for pair in ring for n in pair)
        if key in seen_rings:
            continue
        seen_rings.add(key)
        out.append([edges[p] for p in ring])
    return out


def run(files, repo) -> list[Finding]:
    prog = conc.build_program(files)
    edges = _collect_edges(files, prog)
    by_rel = {f.rel: f for f in files}
    out: list[Finding] = []
    for ring in _cycles(edges):
        # a waiver on ANY edge of the cycle is a reviewed rationale
        # that breaks the ring: suppress the whole cycle
        if any(by_rel[e.rel].waived(e.line, "lockorder-cycle")
               for e in ring if e.rel in by_rel):
            continue
        ring_str = " -> ".join([e.src for e in ring] + [ring[0].src])
        for e in ring:
            out.append(Finding(
                "lockorder-cycle", e.rel, e.line,
                f"lock-order cycle {ring_str}; this edge: "
                f"{e.where} {e.how} while holding '{e.src}'"))
    return out
