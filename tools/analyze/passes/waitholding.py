"""Waiting-while-holding pass (ISSUE 14).

A thread that blocks on an EVENT — a worker joining, a future
resolving, a queue draining — while holding an unrelated mutex couples
the lock's critical section to another thread's progress. If that
other thread ever needs the held lock (directly, or transitively), the
system deadlocks; even when it doesn't, every contender stalls for the
full wait. This is the shape of the gateway-rebind and append-front
close hazards PR 11's review rounds fixed one at a time — the pass
makes the discipline structural.

`wait-holding` flags, inside any `with <lock>:` region (self-attr
locks, lock-list members, module-global locks — recognition shared
with the lockorder pass via conc.py):

  * `X.join()` where X is thread-like (assigned `threading.Thread`,
    or named like one — thread/worker/dispatcher/sender);
  * `X.result()` where X is future-like (fut/future names, or a var
    assigned from `.submit(...)`);
  * `X.wait()` where X is NOT the held lock itself and not a
    condition constructed over a held lock (`Condition(self._lock)`
    waited under `self._lock` RELEASES it — that is the condition
    idiom, never flagged);
  * blocking `X.get(...)`/`X.put(...)` on queue-typed attributes or
    queue-ish names (`*_nowait` variants are non-blocking and exempt).

A bounded timeout does NOT exempt the call — contenders still stall
for the bound, and a bound that papers over a deadlock is exactly the
failure mode the chaos scenarios provoke. Deliberate bounded waits
carry `# analyze: ok wait-holding` with a rationale.
"""

from __future__ import annotations

import ast
import re

from tools.analyze import Finding
from tools.analyze.passes import call_name, dotted
from tools.analyze.passes import conc

NAME = "waitholding"

RULES = {
    "wait-holding": (
        "a join/result/wait/queue-get/put executes while holding a "
        "lock the waited-on work does not own — the critical section "
        "is coupled to another thread's progress (deadlock if that "
        "thread ever needs the held lock; a stall for everyone "
        "otherwise)"),
}

_THREADISH = re.compile(
    r"(^|_)(thread|threads|worker|workers|dispatcher|sender|t)$")
_FUTUREISH = re.compile(r"(^|_)(fut|futs|future|futures|f)$")
_QUEUEISH = re.compile(r"(^|_)(queue|queues|q|inbox|outbox)$|_q$")


def _attr_of_self(expr: ast.AST) -> str | None:
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def _submit_locals(fn: ast.FunctionDef) -> set[str]:
    """Locals assigned from `.submit(...)` / `Future()` — futures."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            leaf = (call_name(node.value) or "").split(".")[-1]
            if leaf in ("submit", "Future"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


class _FnWalk(ast.NodeVisitor):
    def __init__(self, src, fn, cls, prog):
        self.src = src
        self.fn = fn
        self.cls = cls
        self.prog = prog
        self.local_types = conc.fn_local_types(fn, cls, prog)
        self.future_locals = _submit_locals(fn)
        self.held: list[str] = []          # lock nodes
        self.held_attrs: list[str] = []    # raw self-attr names held
        self.findings: list[Finding] = []
        self.where = (f"{cls.name}.{fn.name}" if cls is not None
                      else fn.name)
        for stmt in fn.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):  # noqa: N802 — own thread/scope
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With):  # noqa: N802
        taken = 0
        for item in node.items:
            self.visit(item.context_expr)
            n = conc.with_lock_node(item.context_expr, self.cls,
                                    self.src.rel, self.prog,
                                    self.local_types)
            if n is not None:
                self.held.append(n)
                attr = _attr_of_self(item.context_expr)
                self.held_attrs.append(attr or "")
                taken += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(taken):
            self.held.pop()
            self.held_attrs.pop()

    # ---- call classification ----

    def _target_is_held(self, base: ast.AST) -> bool:
        """The wait target IS (or wraps) a held lock: `cv.wait()` under
        `with cv:`, or a Condition aliased onto a held lock."""
        attr = _attr_of_self(base)
        if attr is not None and self.cls is not None:
            node = self.cls.lock_node(attr)
            if node in self.held:
                return True
            alias = self.cls.cond_alias.get(attr, attr)
            if any(self.cls.cond_alias.get(h, h) == alias
                   for h in self.held_attrs if h):
                return True
            return False
        d = dotted(base)
        if d and "." not in d:
            mod = f"{conc._module_stem(self.src.rel)}:{d}"
            return mod in self.held
        return False

    def _queueish(self, base: ast.AST) -> bool:
        attr = _attr_of_self(base)
        if attr is not None and self.cls is not None and \
                attr in self.cls.queue_attrs:
            return True
        name = attr
        if name is None:
            d = dotted(base)
            name = d.split(".")[-1] if d else None
        if name is None and isinstance(base, ast.Subscript):
            inner = dotted(base.value)
            name = inner.split(".")[-1] if inner else None
        if name is None:
            return False
        if self.local_types.get(name) in ("Queue", "SimpleQueue",
                                          "LifoQueue", "PriorityQueue"):
            return True
        return bool(_QUEUEISH.search(name))

    def _threadish(self, base: ast.AST) -> bool:
        attr = _attr_of_self(base)
        if attr is not None and self.cls is not None and \
                attr in self.cls.thread_attrs:
            return True
        name = attr or (dotted(base) or "").split(".")[-1]
        if not name:
            return False
        if self.local_types.get(name) in ("Thread", "Timer"):
            return True
        return bool(_THREADISH.search(name))

    def _futureish(self, base: ast.AST) -> bool:
        name = _attr_of_self(base) or (dotted(base) or "").split(".")[-1]
        if not name:
            return False
        if name in self.future_locals:
            return True
        return bool(_FUTUREISH.search(name))

    def visit_Call(self, node: ast.Call):  # noqa: N802
        if self.held and isinstance(node.func, ast.Attribute):
            leaf = node.func.attr
            base = node.func.value
            hit = None
            if leaf == "join" and self._threadish(base):
                hit = "join() on a worker thread"
            elif leaf == "result" and self._futureish(base):
                hit = "result() on a future"
            elif leaf == "wait" and not self._target_is_held(base):
                # waiting on the held condition releases it — the
                # condition idiom; anything else blocks while holding
                hit = "wait() on an unrelated event/condition"
            elif leaf in ("get", "put") and self._queueish(base):
                hit = f"blocking {leaf}() on a queue"
            if hit is not None:
                self.findings.append(Finding(
                    "wait-holding", self.src.rel, node.lineno,
                    f"{self.where}: {hit} while holding "
                    f"{sorted(set(self.held))} — the critical section "
                    f"blocks on another thread's progress"))
        self.generic_visit(node)


def run(files, repo) -> list[Finding]:
    prog = conc.build_program(files)
    out: list[Finding] = []
    for src in files:
        jobs = []
        for info in prog.classes:
            if info.rel != src.rel:
                continue
            jobs.extend((m, info) for m in info.methods.values())
        jobs.extend((f, None)
                    for f in prog.module_funcs.get(src.rel, {}).values())
        for fn, cls in jobs:
            out.extend(_FnWalk(src, fn, cls, prog).findings)
    return out
