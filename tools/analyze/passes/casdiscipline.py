"""CAS/epoch/lease discipline for the ownership and replica protocols.

The coordination state — `scheduler/query/*` ownership records,
`cluster/nodes/*` health records, the versioned-config `vcs/*` plane,
and the replica `replica/*` epoch/leader binding — is multi-writer by
design: every server races CAS claims against its peers. The protocol
survives exactly because every write follows three idioms, and each
rule here flags the write shapes that broke (or would have broken)
PR 9/PR 17 review fixes:

  cas-blind-meta-write    a raw `meta_put`/`meta_delete` on a protocol
                          key: last-writer-wins on a multi-writer key
                          silently undoes a concurrent CAS claim. All
                          protocol keys flow through `meta_cas` (or the
                          VersionedConfigStore over it); the follower's
                          single-writer epoch plane is the reviewed
                          exception (waived in store/replica.py).
  cas-put-foreign-version a versioned `config.put`/`config.delete`
                          whose `base_version` does not derive from a
                          `config.get` read in the SAME function: a
                          cached or guessed version turns the CAS into
                          a blind overwrite of whatever raced in
                          between the stale read and the write.
  cas-epoch-nonmonotone   an epoch field assigned from something other
                          than a monotone source (`max(...)`, `+ 1`,
                          `load_epoch`, `boot_epoch`) in a function
                          with no epoch comparison guard: fencing is
                          sound only while epochs never move backwards.
  cas-lease-raw           a heartbeat-age comparison against raw
                          `interval`-derived arithmetic instead of a
                          lease identifier: the placer CLAMPS the lease
                          to >= 3x its tick interval at construction,
                          and any age test that re-derives its own
                          bound from the interval bypasses the clamp
                          (the exact bug of the pre-PR 17 live-adopt).
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import dotted

NAME = "casdiscipline"

RULES = {
    "cas-blind-meta-write": (
        "raw meta_put/meta_delete on a protocol key (scheduler/, "
        "cluster/, vcs/, replica/ or a META_* constant) — "
        "last-writer-wins on a multi-writer key; route it through "
        "meta_cas / the versioned store, or waive the reviewed "
        "single-writer planes"),
    "cas-put-foreign-version": (
        "versioned put/delete whose base_version does not derive from "
        "a config.get read in the same function — a stale or guessed "
        "version makes the CAS overwrite concurrent claims blindly"),
    "cas-epoch-nonmonotone": (
        "epoch field assigned from a non-monotone source in a "
        "function without an epoch comparison guard — fencing is "
        "sound only while epochs never decrease"),
    "cas-lease-raw": (
        "heartbeat-age compared against raw interval arithmetic "
        "instead of the (clamped) lease — re-deriving the bound from "
        "the interval bypasses the 3x-interval lease clamp"),
}

# key prefixes that make a meta key coordination state
_PROTOCOL_PREFIXES = ("scheduler/", "cluster/", "vcs/", "replica/")
# receivers that are VersionedConfigStore instances by convention
_CONFIG_RECV = ("config",)


def _is_protocol_key(node: ast.AST) -> bool:
    """True when the key expression names coordination state: a string
    constant (anywhere in the expression — f-strings, `prefix + qid`
    concatenations) starting with a protocol prefix, or a META_*
    module constant."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if sub.value.startswith(_PROTOCOL_PREFIXES):
                return True
    name = dotted(node)
    if name:
        last = name.rsplit(".", 1)[-1]
        if last.startswith("META_"):
            return True
    return False


def _config_recv(call: ast.Call, method: str) -> bool:
    if not isinstance(call.func, ast.Attribute) \
            or call.func.attr != method:
        return False
    recv = dotted(call.func.value)
    if recv is None:
        return False
    last = recv.rsplit(".", 1)[-1].lstrip("_")
    return last in _CONFIG_RECV


def _functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _tainted_names(fn: ast.AST) -> set[str]:
    """Names whose value derives from a same-function `config.get`
    read. Seed: assignment targets of `<recv>.get(...)` calls on a
    config receiver. Propagate: any assignment whose RHS mentions a
    tainted name taints its targets (covers `version, raw = cur` and
    `v = cur[0]`)."""
    tainted: set[str] = set()

    def targets_of(stmt: ast.Assign) -> list[str]:
        out = []
        for t in stmt.targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    out.append(sub.id)
        return out

    assigns = [s for s in ast.walk(fn) if isinstance(s, ast.Assign)]
    for s in assigns:
        if isinstance(s.value, ast.Call) \
                and _config_recv(s.value, "get"):
            tainted.update(targets_of(s))
    # fixpoint propagation (assignment chains are short)
    for _ in range(4):
        grew = False
        for s in assigns:
            if any(isinstance(sub, ast.Name) and sub.id in tainted
                   for sub in ast.walk(s.value)):
                for name in targets_of(s):
                    if name not in tainted:
                        tainted.add(name)
                        grew = True
        if not grew:
            break
    return tainted


_EPOCH_MONO_CALLS = ("max", "load_epoch")


def _epoch_target(node: ast.AST) -> bool:
    """An lvalue that is a protocol epoch field: `x._epoch`, `x.epoch`
    in a protocol module, or `rec["epoch"]`."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("epoch", "_epoch")
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and sl.value == "epoch"
    return False


def _mentions(node: ast.AST, tokens: set[str]) -> bool:
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        elif isinstance(sub, ast.Constant) \
                and isinstance(sub.value, str):
            ident = sub.value
        if ident and tokens & set(ident.lower().split("_")):
            return True
    return False


def _module_is_protocol(tree: ast.Module) -> bool:
    """The epoch rule only applies to modules touching the REPLICATION
    / ownership epoch plane (load_epoch, boot_epoch, META_EPOCH); the
    engine's `epoch` is a timestamp base, not a fencing token."""
    for sub in ast.walk(tree):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident in ("load_epoch", "boot_epoch", "META_EPOCH"):
            return True
    return False


def _has_epoch_guard(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Compare):
            continue
        if any(isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
               for op in sub.ops):
            if _mentions(sub, {"epoch"}):
                return True
    return False


def _epoch_rhs_monotone(value: ast.AST) -> bool:
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = dotted(sub.func)
            if name and name.rsplit(".", 1)[-1] in _EPOCH_MONO_CALLS:
                return True
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add):
            return True  # epoch bump: cur + 1
        if isinstance(sub, (ast.Name, ast.Attribute)):
            ident = sub.id if isinstance(sub, ast.Name) else sub.attr
            if "boot_epoch" in ident:
                return True
    return False


_AGE_TOKENS = {"age", "hb"}
_INTERVAL_TOKENS = {"interval"}


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        protocol_module = _module_is_protocol(src.tree)

        # ---- cas-blind-meta-write ----------------------------------
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in ("meta_put", "meta_delete"):
                continue
            if not node.args or not _is_protocol_key(node.args[0]):
                continue
            key = dotted(node.args[0])
            if key is None:
                key_const = next(
                    (s.value for s in ast.walk(node.args[0])
                     if isinstance(s, ast.Constant)
                     and isinstance(s.value, str)), "?")
                key = repr(key_const)
            out.append(Finding(
                "cas-blind-meta-write", src.rel, node.lineno,
                f"raw {node.func.attr} on protocol key {key} — "
                f"multi-writer coordination keys go through meta_cas "
                f"or the versioned store"))

        # ---- cas-put-foreign-version / cas-epoch-nonmonotone /
        # ---- cas-lease-raw (per function) --------------------------
        for fn in _functions(src.tree):
            tainted = None  # computed lazily per function
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and (
                        _config_recv(node, "put")
                        or _config_recv(node, "delete")):
                    base = next((kw.value for kw in node.keywords
                                 if kw.arg == "base_version"), None)
                    if base is None and node.func.attr == "delete" \
                            and len(node.args) >= 2:
                        base = node.args[1]
                    if base is None:
                        continue  # create-only put: CAS by absence
                    if isinstance(base, ast.Constant) \
                            and base.value is None:
                        continue
                    if tainted is None:
                        tainted = _tainted_names(fn)
                    names = [s.id for s in ast.walk(base)
                             if isinstance(s, ast.Name)]
                    if not names or any(n not in tainted
                                        for n in names):
                        bad = [n for n in names if n not in tainted]
                        out.append(Finding(
                            "cas-put-foreign-version", src.rel,
                            node.lineno,
                            f"base_version of {node.func.attr} does "
                            f"not derive from a config.get read in "
                            f"this function"
                            + (f" (foreign: {', '.join(sorted(set(bad)))})"
                               if bad else " (constant version)")))

                if protocol_module and isinstance(
                        node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    if not any(_epoch_target(t) for t in targets):
                        continue
                    if isinstance(node, ast.AugAssign):
                        monotone = isinstance(node.op, ast.Add)
                    else:
                        monotone = _epoch_rhs_monotone(node.value)
                    if not monotone and not _has_epoch_guard(fn):
                        out.append(Finding(
                            "cas-epoch-nonmonotone", src.rel,
                            node.lineno,
                            f"epoch assigned in {fn.name} from a "
                            f"non-monotone source with no epoch "
                            f"comparison guard in scope — fencing "
                            f"breaks if an epoch can move backwards"))

                if isinstance(node, ast.Compare):
                    sides = [node.left] + list(node.comparators)
                    age_side = any(_mentions(s, _AGE_TOKENS)
                                   and not _mentions(s, _INTERVAL_TOKENS)
                                   for s in sides)
                    ivl_side = any(_mentions(s, _INTERVAL_TOKENS)
                                   for s in sides)
                    if age_side and ivl_side:
                        out.append(Finding(
                            "cas-lease-raw", src.rel, node.lineno,
                            "heartbeat age compared against raw "
                            "interval arithmetic — use the clamped "
                            "lease (placer clamps lease_ms to >= 3x "
                            "interval at construction)"))
    return out
