"""Dtype-overflow pass for time/sequence identifiers.

Device time is int32 milliseconds relative to a host-managed epoch;
absolute stream time is int64. The safe order of operations is
SUBTRACT IN int64, RANGE-CHECK, THEN NARROW — the epoch-rebase helpers
(`_maybe_rebase`, `_ensure_epoch`, `lattice.rebase`) and the
`(1 << 31)` span guards exist so the narrow can never wrap. The two
ways the discipline silently breaks:

  overflow-ts-arith   arithmetic on an ALREADY-int32-cast timestamp
                      (`ts.astype(np.int32) - epoch`): the subtraction
                      itself wraps long before any later guard can
                      see it. Narrow after the int64 arithmetic, never
                      before.
  overflow-narrowing  an int64->int32 narrow of a time/seq value
                      (`.astype(np.int32)` / `np.int32(...)`) in a
                      host function with NO overflow guard in scope —
                      no `(1 << 31)`/`2**31` comparison, no
                      rebase-threshold reference, no clip, and no call
                      into a `*rebase*`/`_ensure_epoch` helper. Past
                      2^31 ms (~24.8 days of relative time) the value
                      silently goes negative and every window/probe
                      bound derived from it is wrong.

Jitted kernels are exempt: device code COMPUTES in the rebased int32
space by design; the host guards the boundary. Identifier matching is
token-based (`ts`, `time`, `epoch`, `seq`, `lsn`, `watermark`, `wm`,
`start(s)`, plus short `*ts` forms like `bts`/`jts`), so `stats` or
`counts` never match.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import call_name, dotted
from tools.analyze.passes.purity import _jitted_functions

NAME = "overflow"

RULES = {
    "overflow-ts-arith": (
        "arithmetic on an int32-cast timestamp — the operation wraps "
        "before any guard can fire; do the arithmetic in int64, "
        "range-check, then narrow"),
    "overflow-narrowing": (
        "int64->int32 narrow of a time/seq identifier in a host "
        "function with no overflow guard (no (1<<31) check, rebase "
        "reference, or clip) — wraps silently past ~24.8 days of "
        "relative time"),
}

_TOKENS = {"ts", "time", "timestamp", "epoch", "seq", "lsn",
           "watermark", "wm", "start", "starts"}
_GUARD_NAME_PARTS = ("rebase", "_ensure_epoch", "_join_bounds")
_EXEMPT_FN_PARTS = ("rebase", "_join_bounds", "_ensure_epoch")


def _ts_ish(name: str | None) -> bool:
    if not name:
        return False
    for ident in name.split("."):
        for part in ident.lower().split("_"):
            if part in _TOKENS:
                return True
            if part.endswith("ts") and 0 < len(part) <= 3:
                return True  # bts / jts / sts
    return False


def _mentions_ts(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _ts_ish(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and _ts_ish(sub.attr):
            return True
        if isinstance(sub, ast.Constant) and \
                isinstance(sub.value, str) and _ts_ish(sub.value):
            return True  # dict keys: dev["t0"] is epoch state
        if isinstance(sub, ast.Subscript):
            sl = sub.slice
            if isinstance(sl, ast.Constant) and sl.value == "t0":
                return True
    return False


def _int32_cast(node: ast.AST) -> ast.expr | None:
    """The operand being narrowed to int32, or None.

    Shapes: X.astype(np.int32 | 'int32'), np.int32(X), jnp.int32(X),
    np.asarray(X, np.int32) / np.asarray(X, dtype=np.int32)."""
    if not isinstance(node, ast.Call):
        return None
    # the receiver of .astype can be ANY expression ((a - b).astype):
    # read the attribute name directly, not via the dotted-chain helper
    attr = node.func.attr if isinstance(node.func, ast.Attribute) \
        else None
    name = call_name(node) or ""
    leaf = name.split(".")[-1]

    def _is_i32(e: ast.AST) -> bool:
        d = dotted(e)
        if d and d.split(".")[-1] == "int32":
            return True
        return isinstance(e, ast.Constant) and e.value == "int32"

    if attr == "astype" and node.args and _is_i32(node.args[0]):
        return node.func.value
    if leaf == "int32" and name.split(".")[0] in ("np", "numpy",
                                                  "jnp") and node.args:
        return node.args[0]
    if leaf in ("asarray", "array") and node.args:
        if len(node.args) > 1 and _is_i32(node.args[1]):
            return node.args[0]
        for kw in node.keywords:
            if kw.arg == "dtype" and _is_i32(kw.value):
                return node.args[0]
    return None


def _has_guard(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.LShift):
            # `1 << 31` / `1 << 30` — the span-guard idiom
            if isinstance(node.left, ast.Constant) and \
                    isinstance(node.right, ast.Constant) and \
                    node.right.value in (30, 31):
                return True
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow):
            if isinstance(node.right, ast.Constant) and \
                    node.right.value in (30, 31):
                return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted(node) or ""
            leaf = d.split(".")[-1].lower()
            if "rebase" in leaf or leaf in ("clip",):
                return True
        if isinstance(node, ast.Call):
            leaf = (call_name(node) or "").split(".")[-1].lower()
            if any(p in leaf for p in ("rebase", "clip")) or \
                    leaf == "_ensure_epoch":
                return True
    return False


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        jitted = {id(fn) for fn, _how in _jitted_functions(src.tree)}
        # transitive closure: a helper called by bare name from a
        # jitted function executes traced too (pack_extract_rows and
        # friends ARE device code, just not jit-wrapped themselves)
        defs_by_name: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                defs_by_name.setdefault(node.name, []).append(node)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.FunctionDef) or \
                        id(node) not in jitted:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Name):
                        for d in defs_by_name.get(sub.func.id, ()):
                            if id(d) not in jitted:
                                jitted.add(id(d))
                                changed = True
        jitted_nodes: set[int] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef) and id(node) in jitted:
                for sub in ast.walk(node):
                    jitted_nodes.add(id(sub))
        for fn in ast.walk(src.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            if id(fn) in jitted or id(fn) in jitted_nodes:
                continue  # device code: int32 space by design
            if any(p in fn.name for p in _EXEMPT_FN_PARTS):
                continue  # THE sanctioned boundary helpers
            guarded = _has_guard(fn)
            own: list[ast.AST] = []
            nested: set[int] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.FunctionDef) and node is not fn:
                    for inner in ast.walk(node):
                        nested.add(id(inner))
            for node in ast.walk(fn):
                if id(node) in nested or id(node) in jitted_nodes:
                    continue
                own.append(node)
            in_arith: set[int] = set()
            for node in own:
                # arith ON a cast: (x.astype(int32) - y) wraps inside
                if isinstance(node, ast.BinOp) and \
                        isinstance(node.op, (ast.Add, ast.Sub)):
                    for side in (node.left, node.right):
                        op = _int32_cast(side)
                        if op is not None and _mentions_ts(op):
                            in_arith.add(id(side))
                            out.append(Finding(
                                "overflow-ts-arith", src.rel,
                                node.lineno,
                                f"{fn.name}: int32-cast timestamp in "
                                f"+/- arithmetic — narrow AFTER the "
                                f"int64 arithmetic, not before"))
            for node in own:
                if id(node) in in_arith:
                    continue  # already reported as arith-on-cast
                # bare narrow without a guard in scope
                op = _int32_cast(node)
                if op is not None and _mentions_ts(op) and not guarded:
                    out.append(Finding(
                        "overflow-narrowing", src.rel, node.lineno,
                        f"{fn.name}: int32 narrow of a time/seq value "
                        f"with no overflow guard in the function — "
                        f"add a (1<<31) span check or route through "
                        f"the rebase helpers"))
    return out
