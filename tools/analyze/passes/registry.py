"""Registry pass: the observability registries match their call sites
(absorbs tools/metrics_lint.py — ISSUE 3's X-macro-discipline lint).

The reference gets this for free: a metric exists iff its `.inc` line
compiles. Python defers the mistake to runtime (a KeyError on a cold
path, or a histogram nobody ever looks for), so the pass restores the
compile-time property in both directions:

  registry-unknown  a `stream_stat_add` / `time_series_add` /
                    `gauge_set` / `gauge_fn` / `observe` /
                    `events.append(kind, ...)` call site whose metric
                    argument is a string literal names a metric absent
                    from the registries (hstream_tpu/stats);
  registry-dead     a registered metric / event kind is referenced by
                    no call site anywhere in production code — dead
                    registry entries rot dashboards (this is how the
                    dead `append_failed` counter was found in PR 3).

Dynamic call sites (metric passed as a variable) are skipped — those
hit the registries' own KeyError at runtime. Literal mentions inside
the registry/exposition modules and tools/ give no liveness credit.
"""

from __future__ import annotations

import ast
import sys

from tools.analyze import Finding

NAME = "registry"

RULES = {
    "registry-unknown": (
        "metric/event call site names a string literal absent from "
        "the stats registries — a typo that would KeyError on a cold "
        "path"),
    "registry-dead": (
        "registered metric/event kind referenced by no production "
        "call site — a dead registry entry"),
    "registry-stage": (
        "trace-span stage / kernel-family literal absent from the "
        "declared sets (tracing.TRACE_STAGES / KERNEL_FAMILIES) — a "
        "renamed stage silently orphans its histogram series and its "
        "spans"),
    "registry-family": (
        "stat_add/stat_rate/... call site names a stat family absent "
        "from the declared table (stats/families.STAT_FAMILIES) — the "
        "X-macro property: a family exists iff its table row does, so "
        "an undeclared name would KeyError on a cold path and never "
        "reach the admin/exposition/federation surfaces"),
}

COUNTER_CALLS = {"stream_stat_add", "stream_stat_get",
                 "stream_stat_getall"}
TS_CALLS = {"time_series_add", "time_series_get_rate",
            "time_series_peek_rate", "time_series_streams", "_ts"}
# the declarative-family API (ISSUE 15): same registry kind as the
# legacy time-series shims (both resolve against STAT_FAMILIES), but
# violations report under their own rule — the `.inc` discipline the
# families table exists to enforce
FAMILY_CALLS = {"stat_add", "stat_rate", "stat_sum", "stat_avg",
                "stat_count", "stat_ladder", "stat_keys",
                "_family_series", "_peek_series"}
GAUGE_CALLS = {"gauge_set", "gauge_fn", "gauge_drop", "gauge_labels"}
HIST_CALLS = {"observe", "histogram_percentile", "_hist"}

# stage/family-literal call shapes (ISSUE 13): call name -> (positional
# index of the stage literal, declared-set kind). The spans and the
# stage-labeled histogram series both key on these names, so a rename
# at one call site silently forks the series.
STAGE_ARG_CALLS = {
    "trace_span": (1, "stage"),
    "record_span": (1, "stage"),
    "_observe_append_stage": (0, "stage"),
    "_trace_stage_span": (1, "stage"),
    "kernel_family": (0, "family"),
}
# histograms whose LABEL argument is a stage name
STAGE_LABELED_HISTOGRAMS = {"stage_latency_ms", "freshness_lag_ms"}

# files whose literals do NOT count as "referenced" for the dead-entry
# check: the registries themselves, the exposition layer (HELP text
# names every metric), and tools (a metric only lint mentions is still
# dead in production)
_NO_REFERENCE_CREDIT = (
    "hstream_tpu/stats/__init__.py",
    "hstream_tpu/stats/events.py",
    "hstream_tpu/stats/families.py",
    "hstream_tpu/stats/timeseries.py",
    "hstream_tpu/stats/prometheus.py",
    "tools",
)

REGISTRY_FILE = "hstream_tpu/stats/__init__.py"


def _registries(repo: str) -> dict[str, set[str]]:
    """Import the live registries from the tree under analysis."""
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from hstream_tpu.common.tracing import KERNEL_FAMILIES, TRACE_STAGES
    from hstream_tpu.stats import (
        GAUGES,
        HISTOGRAMS,
        PER_STREAM_COUNTERS,
    )
    from hstream_tpu.stats.events import EVENT_KINDS
    from hstream_tpu.stats.families import FAMILY_NAMES

    return {
        "counter": set(PER_STREAM_COUNTERS),
        # the declarative family table: the legacy time-series shims
        # and the stat_* API both resolve against it
        "time_series": set(FAMILY_NAMES),
        "gauge": set(GAUGES),
        "histogram": {name for name, _b, _l in HISTOGRAMS},
        "event": set(EVENT_KINDS),
        # stage/family vocabularies are checked in the UNKNOWN
        # direction only: their names are common words, so a literal
        # scan cannot prove deadness
        "stage": set(TRACE_STAGES),
        "family": set(KERNEL_FAMILIES),
    }


_CALL_KIND: dict[str, str] = {}
for _n in COUNTER_CALLS:
    _CALL_KIND[_n] = "counter"
for _n in TS_CALLS:
    _CALL_KIND[_n] = "time_series"
for _n in FAMILY_CALLS:
    _CALL_KIND[_n] = "time_series"
for _n in GAUGE_CALLS:
    _CALL_KIND[_n] = "gauge"
for _n in HIST_CALLS:
    _CALL_KIND[_n] = "histogram"


def _method_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_events_append(call: ast.Call) -> bool:
    """`<something>.events.append(...)` / `journal.append(...)` /
    `self._journal(...)`: the event-kind call shapes used in-tree."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "append":
        base = fn.value
        base_name = (base.attr if isinstance(base, ast.Attribute)
                     else base.id if isinstance(base, ast.Name) else "")
        return base_name in ("events", "journal", "_events", "_ring")
    if isinstance(fn, ast.Attribute) and fn.attr == "_journal":
        return True
    return False


def run(files, repo) -> list[Finding]:
    registries = _registries(repo)
    out: list[Finding] = []
    referenced: dict[str, set[str]] = {k: set() for k in registries}
    all_names = {n for names in registries.values() for n in names}
    for src in files:
        if not src.rel.startswith(_NO_REFERENCE_CREDIT):
            # dead-entry credit: ANY literal mention in production code
            # (call sites, routing dicts like handlers._RPC_HISTOGRAMS)
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and node.value in all_names):
                    for kind, names in registries.items():
                        if node.value in names:
                            referenced[kind].add(node.value)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _method_name(node)
            # stage/family literals sit at varying positions; dynamic
            # names are skipped like every other registry check
            ent = STAGE_ARG_CALLS.get(name or "")
            if ent is not None:
                idx, skind = ent
                if len(node.args) > idx:
                    arg = node.args[idx]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                            and arg.value not in registries[skind]):
                        out.append(Finding(
                            "registry-stage", src.rel, node.lineno,
                            f"{name}(... {arg.value!r} ...) names an "
                            f"undeclared {skind} (tracing."
                            f"{'TRACE_STAGES' if skind == 'stage' else 'KERNEL_FAMILIES'})"))
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # dynamic name: runtime KeyError covers it
            if (name in HIST_CALLS
                    and first.value in STAGE_LABELED_HISTOGRAMS
                    and len(node.args) > 1):
                lab = node.args[1]
                if (isinstance(lab, ast.Constant)
                        and isinstance(lab.value, str)
                        and lab.value not in registries["stage"]):
                    out.append(Finding(
                        "registry-stage", src.rel, node.lineno,
                        f"{name}({first.value!r}, {lab.value!r}, ...) "
                        f"labels a stage histogram with an undeclared "
                        f"stage (tracing.TRACE_STAGES)"))
            kind = _CALL_KIND.get(name or "")
            if kind is not None:
                metric = first.value
                if metric in registries[kind]:
                    referenced[kind].add(metric)
                elif name in FAMILY_CALLS:
                    out.append(Finding(
                        "registry-family", src.rel, node.lineno,
                        f"{name}({metric!r}, ...) names a stat "
                        f"family absent from the declared table "
                        f"(stats/families.STAT_FAMILIES)"))
                else:
                    out.append(Finding(
                        "registry-unknown", src.rel, node.lineno,
                        f"{name}({metric!r}, ...) names an "
                        f"unregistered {kind} metric"))
            elif _is_events_append(node):
                event = first.value
                if event in registries["event"]:
                    referenced["event"].add(event)
                else:
                    out.append(Finding(
                        "registry-unknown", src.rel, node.lineno,
                        f"events.append({event!r}) names an "
                        f"unregistered event kind"))
    # direction 2: registered but never referenced anywhere (stage/
    # family vocabularies excluded — see _registries)
    for kind, names in sorted(registries.items()):
        if kind in ("stage", "family"):
            continue
        for name in sorted(names - referenced[kind]):
            out.append(Finding(
                "registry-dead", REGISTRY_FILE, 1,
                f"{kind} metric {name!r} is registered but never "
                f"referenced by any call site"))
    return out
