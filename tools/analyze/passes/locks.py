"""Lock-discipline pass (RacerD-style ownership inference, per class).

lock-guard — infer each class's guarded-attribute set from its own
majority idiom, PER LOCK: an attribute is guarded by lock L when it is
WRITTEN at least once inside a `with self.L:` block and touched inside
`with self.L:` blocks in >= 2 distinct methods (so one incidental
locked access doesn't promote an attribute). Any access not holding a
guarding lock — including one holding only some OTHER lock of the
class — is a finding. Exemptions encode the repo's conventions:

  * `__init__` (the object is not shared yet);
  * methods whose name ends in `_locked` or whose docstring says the
    caller holds the lock — they run under the caller's critical
    section, so their accesses count as guarded for inference AND are
    never flagged;
  * lock attributes themselves (acquiring `self._lock` is not an access
    to guarded state).

lock-order — methods that nest two `with self.<lock>` acquisitions
define an order edge (outer -> inner) for the class; two methods with
contradictory edges (A->B somewhere, B->A elsewhere) can deadlock under
the right interleaving. Both sites are flagged.
"""

from __future__ import annotations

import ast
import re

from tools.analyze import Finding
from tools.analyze.passes import class_methods, dotted, walk_classes

NAME = "locks"

RULES = {
    "lock-guard": (
        "attribute written under `with self.<lock>` and locked under "
        "that same lock in >=2 methods is guarded by it; accessing it "
        "without holding a guarding lock (even under another lock) "
        "races the locked writers"),
    "lock-order": (
        "two methods of one class acquire the same two locks in "
        "opposite nesting order — a deadlock under the right "
        "interleaving"),
}

# attribute names that look like locks: threading.Lock/RLock/Condition
# holders by convention (self._lock, self.lock, self._cond, ...)
_LOCKISH = re.compile(r"(^|_)(lock|cond|cv|mutex|mutate)$|_lock$|_cv$")

_HELD_DOC = re.compile(r"caller holds|holding (self|the) lock|"
                       r"lock (is )?held", re.I)


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    """Attributes of `cls` that are used as locks (appear as `with
    self.X:` anywhere) or are assigned a Lock/RLock/Condition in any
    method."""
    by_name: set[str] = set()
    by_type: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                d = dotted(item.context_expr)
                if d and d.startswith("self.") and d.count(".") == 1:
                    by_name.add(d.split(".", 1)[1])
        elif isinstance(node, ast.Assign):
            v = node.value
            if isinstance(v, ast.Call):
                cn = dotted(v.func) or ""
                leaf = cn.split(".")[-1]
                # locktrace factories (ISSUE 14): named traced locks
                # are locks for every inference purpose
                if leaf in ("Lock", "RLock", "Condition", "Semaphore",
                            "BoundedSemaphore", "TracedLock") or \
                        cn in ("locktrace.lock", "locktrace.rlock",
                               "locktrace.lock_list"):
                    for tgt in node.targets:
                        d = dotted(tgt)
                        if d and d.startswith("self."):
                            by_type.add(d.split(".", 1)[1])
    # a `with self.X:` target is a lock iff it LOOKS like one (the name
    # check keeps accidental context managers out); an attribute
    # assigned a Lock/Condition is one regardless of name
    return {a for a in by_name if _LOCKISH.search(a)} | by_type


def _runs_locked(fn: ast.FunctionDef) -> bool:
    """Method documented to run under the caller's lock."""
    if fn.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(fn) or ""
    return bool(_HELD_DOC.search(doc))


class _MethodScan(ast.NodeVisitor):
    """Classify every `self.X` access in one method as locked (inside a
    `with self.<lock>:` block) or not. Nested function defs are skipped
    — they run on other threads/contexts with their own discipline."""

    def __init__(self, lock_attrs: set[str], fn: ast.FunctionDef):
        self.lock_attrs = lock_attrs
        # (attr, line, is_write, held locks at the access)
        self.accesses: list[tuple[str, int, bool, frozenset[str]]] = []
        self.with_stack: list[list[str]] = []  # lock names per With
        self.order_edges: list[tuple[str, str, int]] = []
        self._fn = fn
        for stmt in fn.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):  # noqa: N802 — skip nested defs
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With):  # noqa: N802
        held = []
        for item in node.items:
            d = dotted(item.context_expr)
            if d and d.startswith("self.") and d.count(".") == 1:
                attr = d.split(".", 1)[1]
                if attr in self.lock_attrs:
                    held.append(attr)
                    for outer in [a for frame in self.with_stack
                                  for a in frame]:
                        if outer != attr:
                            self.order_edges.append(
                                (outer, attr, node.lineno))
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars:
                self.visit(item.optional_vars)
        self.with_stack.append(held)
        for stmt in node.body:
            self.visit(stmt)
        self.with_stack.pop()

    def visit_Attribute(self, node: ast.Attribute):  # noqa: N802
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr not in self.lock_attrs):
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            held = frozenset(a for frame in self.with_stack
                             for a in frame)
            self.accesses.append(
                (node.attr, node.lineno, is_write, held))
        self.generic_visit(node)


def infer_guards(cls: ast.ClassDef
                 ) -> tuple[set[str], dict[str, set[str]], dict]:
    """(lock attrs, attr -> guarding locks, method scans) for one
    class — the per-class ownership inference, shared with the
    atomicity pass (ISSUE 14)."""
    lock_attrs = _lock_attrs(cls)
    if not lock_attrs:
        return set(), {}, {}
    methods = list(class_methods(cls))
    scans = {m.name: (_MethodScan(lock_attrs, m), m) for m in methods}

    # inference is PER LOCK: the guarding lock of an attribute is the
    # one it is written under and accessed under in >= 2 methods — an
    # access holding only some OTHER lock still races the real guard.
    # A "caller holds the lock" method can't name which lock: its
    # accesses credit every lock of the class.
    locked_in: dict[tuple[str, str], set[str]] = {}  # (attr,lock)->methods
    written_under: dict[str, set[str]] = {}          # attr -> locks
    for name, (scan, fn) in scans.items():
        under_all = frozenset(lock_attrs) if _runs_locked(fn) else None
        for attr, _line, is_write, held in scan.accesses:
            for lock in (under_all or held):
                locked_in.setdefault((attr, lock), set()).add(name)
                if is_write:
                    written_under.setdefault(attr, set()).add(lock)
    guards: dict[str, set[str]] = {}  # attr -> inferred guarding locks
    for (attr, lock), ms in locked_in.items():
        if len(ms) >= 2 and lock in written_under.get(attr, ()):
            guards.setdefault(attr, set()).add(lock)
    return lock_attrs, guards, scans


def _scan_class(src, cls: ast.ClassDef) -> list[Finding]:
    lock_attrs, guards, scans = infer_guards(cls)
    if not lock_attrs:
        return []

    out: list[Finding] = []
    for name, (scan, fn) in scans.items():
        if name == "__init__" or _runs_locked(fn):
            continue
        for attr, line, is_write, held in scan.accesses:
            locks_for = guards.get(attr)
            if locks_for and not (held & locks_for):
                kind = "write to" if is_write else "read of"
                wrong = (f" while holding only {sorted(held)}"
                         if held else "")
                out.append(Finding(
                    "lock-guard", src.rel, line,
                    f"{cls.name}.{name}: unguarded {kind} '{attr}'"
                    f"{wrong} (guarded by {sorted(locks_for)})"))

    # lock-order: contradictory edges across the class
    edges: dict[tuple[str, str], int] = {}
    for name, (scan, _fn) in scans.items():
        for a, b, line in scan.order_edges:
            edges.setdefault((a, b), line)
    # both sites are flagged with their own line; the MESSAGE (a
    # baseline key) stays line-free so drift cannot resurrect it
    for (a, b), line in sorted(edges.items()):
        if (b, a) in edges and a < b:
            out.append(Finding(
                "lock-order", src.rel, line,
                f"{cls.name}: locks '{a}' and '{b}' are acquired in "
                f"both orders"))
            out.append(Finding(
                "lock-order", src.rel, edges[(b, a)],
                f"{cls.name}: locks '{b}' and '{a}' are acquired in "
                f"both orders"))
    return out


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        for cls in walk_classes(src.tree):
            out.extend(_scan_class(src, cls))
    return out
