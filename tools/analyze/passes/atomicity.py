"""Check-then-act pass (ISSUE 14).

The `lock-guard` rule proves every ACCESS to guarded state holds the
guard — but a correctly-locked read whose DECISION executes after
release is still a race: the supervisor's corpse/cancel bugs (PR 8's
review caught them by hand) were exactly this shape — read a task's
status under the lock, release, then re-take the lock and mutate based
on the now-stale verdict.

`atomicity-check-act` flags the statically recognizable core of that
bug class, per class (reusing the locks pass's ownership inference):

  1. a local is assigned from a read of a guarded attribute inside
     `with self.<lock>:`;
  2. after the block exits, that local is the test (or part of the
     test) of an `if`/`while` OUTSIDE any block holding the guard;
  3. the taken branch writes an attribute guarded by the SAME lock —
     directly, or under a RE-acquired `with self.<lock>:`.

Step 3's re-acquired form is the one `lock-guard` cannot see: every
individual access is locked, yet check and act run in different
critical sections. Suppressions encode the repo's correct idioms:

  * a branch whose re-acquired block RE-CHECKS guarded state (an
    `if`/`while` test inside the `with` that reads any attribute the
    lock guards) is the check-twice idiom — clean;
  * a read variable only RETURNED / reported (never branching into a
    guarded write) is the snapshot idiom — clean;
  * `__init__` and caller-holds (`*_locked`) methods are exempt, like
    the locks pass.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import class_methods, walk_classes
from tools.analyze.passes.locks import _lock_attrs, _runs_locked

NAME = "atomicity"

RULES = {
    "atomicity-check-act": (
        "a guarded read's decision executes after the lock is "
        "released: the branch re-acquires the lock (or writes "
        "unguarded) and mutates guarded state based on a stale "
        "verdict — check and act must share one critical section"),
}


# container mutators: calling one of these ON a guarded attribute is a
# write to the guarded state, exactly like a plain store
_MUTATORS = frozenset({
    "pop", "append", "add", "remove", "clear", "discard", "update",
    "insert", "extend", "setdefault", "popitem", "put", "appendleft",
    "popleft"})


def _self_attr_accesses(fn: ast.FunctionDef, lock_attrs: set[str]):
    """(attr, is_write, held-locks) triples for one method, with
    WRITES broadened over the locks pass: subscript stores
    (`self.X[k] = v`), deletes, and container mutator calls
    (`self.X.pop(...)`) count — check-then-act races live in exactly
    those container updates. Nested defs are skipped."""
    out: list[tuple[str, bool, frozenset[str]]] = []
    skip: set[int] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and sub is not fn:
            for inner in ast.walk(sub):
                skip.add(id(inner))

    def attr_of(node: ast.AST) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in lock_attrs):
            return node.attr
        return None

    def scan_expr(node: ast.AST, held: tuple[str, ...]) -> None:
        for sub in ast.walk(node):
            if id(sub) in skip:
                continue
            a = attr_of(sub)
            if a is not None:
                write = isinstance(sub.ctx, (ast.Store, ast.Del))
                out.append((a, write, frozenset(held)))
            elif isinstance(sub, ast.Subscript) and \
                    isinstance(sub.ctx, (ast.Store, ast.Del)):
                a = attr_of(sub.value)
                if a is not None:
                    out.append((a, True, frozenset(held)))
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _MUTATORS:
                a = attr_of(sub.func.value)
                if a is not None:
                    out.append((a, True, frozenset(held)))

    def walk(stmts, held: tuple[str, ...]):
        for stmt in stmts:
            if id(stmt) in skip or isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.With):
                taken = list(held)
                for item in stmt.items:
                    scan_expr(item.context_expr, held)
                    e = item.context_expr
                    if (isinstance(e, ast.Attribute)
                            and isinstance(e.value, ast.Name)
                            and e.value.id == "self"
                            and e.attr in lock_attrs):
                        taken.append(e.attr)
                walk(stmt.body, tuple(taken))
            elif isinstance(stmt, (ast.If, ast.While)):
                scan_expr(stmt.test, held)
                walk(stmt.body, held)
                walk(stmt.orelse, held)
            elif isinstance(stmt, ast.For):
                scan_expr(stmt.iter, held)
                scan_expr(stmt.target, held)
                walk(stmt.body, held)
                walk(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, held)
                for h in stmt.handlers:
                    walk(h.body, held)
                walk(stmt.orelse, held)
                walk(stmt.finalbody, held)
            else:
                scan_expr(stmt, held)

    walk(fn.body, ())
    return out


def infer_guards_broad(cls: ast.ClassDef
                       ) -> tuple[set[str], dict[str, set[str]], dict]:
    """Per-class guard inference with container-write recognition:
    attr guarded by L when (broadly) WRITTEN under L and touched under
    L in >= 2 methods — the locks-pass rule over richer writes."""
    lock_attrs = _lock_attrs(cls)
    if not lock_attrs:
        return set(), {}, {}
    methods = {m.name: m for m in class_methods(cls)}
    locked_in: dict[tuple[str, str], set[str]] = {}
    written_under: dict[str, set[str]] = {}
    for name, fn in methods.items():
        under_all = frozenset(lock_attrs) if _runs_locked(fn) else None
        for attr, is_write, held in _self_attr_accesses(fn, lock_attrs):
            for lock in (under_all or held):
                locked_in.setdefault((attr, lock), set()).add(name)
                if is_write:
                    written_under.setdefault(attr, set()).add(lock)
    guards: dict[str, set[str]] = {}
    for (attr, lock), ms in locked_in.items():
        if len(ms) >= 2 and lock in written_under.get(attr, ()):
            guards.setdefault(attr, set()).add(lock)
    return lock_attrs, guards, methods


def _guarded_reads(expr: ast.AST, guards: dict[str, set[str]],
                   held: set[str]) -> set[str]:
    """Attrs read in `expr` that are guarded by a currently-held lock."""
    out: set[str] = set()
    for node in ast.walk(expr):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)
                and guards.get(node.attr, set()) & held):
            out.add(node.attr)
    return out


def _names_in(expr: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


class _Method(ast.NodeVisitor):
    """Single linear walk of one method tracking (a) the held-lock
    stack, (b) locals carrying guarded reads, (c) branch tests on
    those locals outside the guard."""

    def __init__(self, src, cls_name, fn, guards, lock_attrs):
        self.src = src
        self.cls_name = cls_name
        self.fn = fn
        self.guards = guards
        self.lock_attrs = lock_attrs
        self.held: list[str] = []
        # var -> (guarded attr, lock held at the read, read line)
        self.carriers: dict[str, tuple[str, str, int]] = {}
        self.findings: list[Finding] = []
        for stmt in fn.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):  # noqa: N802 — own scope
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With):  # noqa: N802
        taken = []
        for item in node.items:
            d = item.context_expr
            attr = None
            if (isinstance(d, ast.Attribute)
                    and isinstance(d.value, ast.Name)
                    and d.value.id == "self"
                    and d.attr in self.lock_attrs):
                attr = d.attr
            if attr is not None:
                self.held.append(attr)
                taken.append(attr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in taken:
            self.held.pop()

    def visit_Assign(self, node: ast.Assign):  # noqa: N802
        held = set(self.held)
        reads = _guarded_reads(node.value, self.guards, held)
        targets = [t.id for t in node.targets
                   if isinstance(t, ast.Name)]
        for t in targets:
            if reads and held:
                attr = sorted(reads)[0]
                lock = sorted(self.guards[attr] & held)[0]
                self.carriers[t] = (attr, lock, node.lineno)
            else:
                self.carriers.pop(t, None)  # rebound: stops carrying
        self.generic_visit(node)

    def _branch_acts(self, body: list[ast.stmt], lock: str) -> bool:
        """Does the branch write state guarded by `lock` — directly
        (unguarded) or under a re-acquired `with self.<lock>:` that
        does NOT re-check guarded state first?"""
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.With):
                    reacquires = any(
                        isinstance(i.context_expr, ast.Attribute)
                        and isinstance(i.context_expr.value, ast.Name)
                        and i.context_expr.value.id == "self"
                        and i.context_expr.attr == lock
                        for i in node.items)
                    if not reacquires:
                        continue
                    rechecks = any(
                        isinstance(sub, (ast.If, ast.While))
                        and _guarded_reads(sub.test, self.guards,
                                           {lock})
                        for w in node.body for sub in ast.walk(w))
                    if rechecks:
                        continue
                    if self._writes_guarded(node, lock):
                        return True
                elif (isinstance(node, ast.Attribute)
                      and isinstance(node.ctx, (ast.Store, ast.Del))
                      and isinstance(node.value, ast.Name)
                      and node.value.id == "self"
                      and lock in self.guards.get(node.attr, ())):
                    return True  # unguarded direct write
        return False

    def _writes_guarded(self, tree: ast.AST, lock: str) -> bool:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, (ast.Store, ast.Del))
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and lock in self.guards.get(node.attr, ())):
                return True
            # container mutation: self._pending.pop(...), .append(...)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                base = node.func.value
                if (isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                        and lock in self.guards.get(base.attr, ())
                        and node.func.attr in (
                            "pop", "append", "add", "remove", "clear",
                            "discard", "update", "insert", "extend",
                            "setdefault", "popitem", "put")):
                    return True
        return False

    def _check_test(self, node) -> None:
        if self.held:
            return  # decision still under some lock of the class
        for name in _names_in(node.test):
            hit = self.carriers.get(name)
            if hit is None:
                continue
            attr, lock, read_line = hit
            branches = [node.body] + ([node.orelse] if node.orelse
                                      else [])
            if any(self._branch_acts(b, lock) for b in branches):
                self.findings.append(Finding(
                    "atomicity-check-act", self.src.rel, node.lineno,
                    f"{self.cls_name}.{self.fn.name}: decision on "
                    f"'{name}' (read of '{attr}' under "
                    f"'{lock}' at a released critical section) acts "
                    f"on '{lock}'-guarded state after release — "
                    f"check and act are two critical sections"))
                break

    def visit_If(self, node: ast.If):  # noqa: N802
        self._check_test(node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):  # noqa: N802
        self._check_test(node)
        self.generic_visit(node)


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        for cls in walk_classes(src.tree):
            lock_attrs, guards, methods = infer_guards_broad(cls)
            if not guards:
                continue
            for name, fn in methods.items():
                if name == "__init__" or _runs_locked(fn):
                    continue
                out.extend(_Method(src, cls.name, fn, guards,
                                   lock_attrs).findings)
    return out
