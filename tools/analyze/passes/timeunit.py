"""Millisecond/second unit mixing.

The protocol plane measures time in integer MILLISECONDS
(`hb_ms`, `lease_ms`, `created_time_ms`, `now_ms()`), the stdlib
measures in float SECONDS (`time.time()`, `time.monotonic()`,
`timeout_s`, `lease_timeout_s`). Both conventions are fine; an
expression combining them without a conversion is not — a lease
compared against `time.time()` is off by 1000x and every owner reads
as dead (or never dead). The convention is spelled in the suffix, so
the mix is statically visible:

  timeunit-mix   a single arithmetic (+/-) or comparison expression
                 with one operand in ms (identifier suffix `_ms`/
                 `_msec`, or bare `ms`) and another in seconds
                 (suffix `_s`/`_sec`/`_secs`/`_seconds`, bare
                 `seconds`, or a direct `time.time()`/
                 `time.monotonic()` call) and NO recognized conversion
                 factor (1000 / 1000.0 / 1e3 / 0.001) anywhere in the
                 expression.

Conversions like `time.time() * 1e3 - dur_ms` pass (the factor is in
the expression); genuinely mixed-unit code has no factor to find.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import call_name

NAME = "timeunit"

RULES = {
    "timeunit-mix": (
        "arithmetic or comparison mixing a *_ms operand with a "
        "seconds operand (*_s / *_sec / time.time() / "
        "time.monotonic()) without a 1000/1e3/0.001 conversion "
        "factor in the expression — off by 1000x"),
}

_MS_SUFFIXES = {"ms", "msec", "msecs"}
_S_SUFFIXES = {"s", "sec", "secs", "seconds"}
_S_CALLS = {"time.time", "time.monotonic"}
_FACTORS = {1000, 1000.0, 1e3, 0.001}


def _unit_of_ident(ident: str) -> str | None:
    last = ident.lower().split("_")[-1]
    if last in _MS_SUFFIXES:
        return "ms"
    if last in _S_SUFFIXES and "_" in ident or ident == "seconds":
        # bare names like `stats`/`args` must not read as seconds:
        # the s-suffix only counts after an underscore (`timeout_s`)
        return "s"
    return None


def _units(node: ast.AST) -> set[str]:
    """Units mentioned anywhere inside one operand subtree."""
    units: set[str] = set()
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        elif isinstance(sub, ast.Call):
            name = call_name(sub)
            if name and name.rsplit(".", 1)[-1] in ("time", "monotonic") \
                    and name in _S_CALLS:
                units.add("s")
            continue
        if ident:
            u = _unit_of_ident(ident)
            if u:
                units.add(u)
    return units


def _has_factor(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) \
                and isinstance(sub.value, (int, float)) \
                and not isinstance(sub.value, bool) \
                and sub.value in _FACTORS:
            return True
    return False


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        flagged: set[int] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Add, ast.Sub)):
                operands = [node.left, node.right]
            elif isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
            else:
                continue
            if node.lineno in flagged:
                continue
            per_op = [_units(o) for o in operands]
            has_ms = any("ms" in u for u in per_op)
            has_s = any(u == {"s"} for u in per_op)
            if not (has_ms and has_s):
                continue
            if _has_factor(node):
                continue
            flagged.add(node.lineno)
            kind = ("comparison" if isinstance(node, ast.Compare)
                    else "arithmetic")
            out.append(Finding(
                "timeunit-mix", src.rel, node.lineno,
                f"{kind} mixes millisecond and second operands with "
                f"no conversion factor in the expression"))
    return out
