"""shard_map hygiene pass.

The sharded lattice runs its hot step under `jax.shard_map` with zero
collectives; merges (psum/pmin/pmax over the data axis) ride ICI only
at drain points. Three ways that discipline breaks, each invisible to
single-device tests (single-chip runs never bind a mesh axis):

  shardmap-collective  a `jax.lax.p*` collective in a function that is
                       never wrapped by shard_map (directly, or called
                       from a shard_map body in the same module) — an
                       unbound axis name raises at trace time on the
                       first REAL mesh run.
  shardmap-callback    a host callback / fetch (`jax.debug.*`,
                       `io_callback`/`pure_callback`/`host_callback`,
                       `np.asarray`, `.item()`, `device_get`, `print`)
                       inside a shard_map body: per-shard host syncs
                       serialize the mesh and deadlock multi-host
                       meshes.
  shardmap-axis        a collective naming a LITERAL axis that no
                       Mesh(...)/axis declaration in the module spells
                       — a typo that trips only on mesh hardware.

Body discovery mirrors the purity pass (functions passed to shard_map
by name, nested construction, decorator form) and then closes over
same-module helpers called BY those bodies (`merged_col` called from
`extract_local` is mesh code too).
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import call_name, dotted

NAME = "shardmap"

RULES = {
    "shardmap-collective": (
        "jax.lax collective outside any shard_map body — the axis "
        "name is unbound; raises at trace time on a real mesh"),
    "shardmap-callback": (
        "host callback/fetch inside a shard_map body — per-shard "
        "host syncs serialize the mesh and deadlock multi-host runs"),
    "shardmap-axis": (
        "collective names an axis literal no Mesh/axis declaration in "
        "the module spells — a typo that only trips on mesh hardware"),
}

_COLLECTIVES = {"psum", "pmin", "pmax", "pmean", "all_gather",
                "ppermute", "all_to_all", "axis_index", "pshuffle",
                "psum_scatter"}
_CALLBACKS = {"io_callback", "pure_callback", "host_callback",
              "callback", "print", "breakpoint"}
_FETCHES = {"asarray", "item", "device_get", "block_until_ready"}


def _shard_map_bodies(tree: ast.Module) -> set[int]:
    """ids of FunctionDefs that execute inside shard_map, closed over
    same-module callees."""
    defs_by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, []).append(node)

    def is_sm(name: str | None) -> bool:
        return bool(name) and name.split(".")[-1] == "shard_map"

    body_ids: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = dotted(d)
                inner = None
                if isinstance(dec, ast.Call) and name and \
                        name.split(".")[-1] == "partial" and dec.args:
                    inner = dotted(dec.args[0])
                if is_sm(name) or is_sm(inner):
                    body_ids.add(id(node))
        elif isinstance(node, ast.Call) and is_sm(call_name(node)):
            args = list(node.args)
            if args and isinstance(args[0], ast.Name):
                for fn in defs_by_name.get(args[0].id, ()):
                    body_ids.add(id(fn))

    # transitive closure: helpers called from shard_map bodies by bare
    # name are mesh code too
    changed = True
    while changed:
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef) or \
                    id(node) not in body_ids:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name):
                    for fn in defs_by_name.get(sub.func.id, ()):
                        if id(fn) not in body_ids:
                            body_ids.add(id(fn))
                            changed = True
    return body_ids


def _declared_axes(tree: ast.Module) -> set[str]:
    """Axis-name string literals declared in the module: Mesh(...)
    arguments, `axis_names=`/`*_axis=` keywords, and `*_axis`
    parameter defaults."""
    axes: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            leaf = (call_name(node) or "").split(".")[-1]
            if leaf == "Mesh":
                for arg in node.args[1:]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            axes.add(sub.value)
            for kw in node.keywords:
                if kw.arg and (kw.arg == "axis_names"
                               or kw.arg.endswith("_axis")):
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            axes.add(sub.value)
        elif isinstance(node, ast.FunctionDef):
            args = node.args.posonlyargs + node.args.args
            defaults = node.args.defaults
            for a, d in zip(args[len(args) - len(defaults):], defaults):
                if a.arg.endswith("_axis") and \
                        isinstance(d, ast.Constant) and \
                        isinstance(d.value, str):
                    axes.add(d.value)
    return axes


def _axis_literal(call: ast.Call) -> str | None:
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis_index_groups") and \
                isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value  # axis_index("data")
    return None


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        bodies = _shard_map_bodies(src.tree)
        if not bodies and not any(
                isinstance(n, ast.Call)
                and (call_name(n) or "").split(".")[-1] in _COLLECTIVES
                and (call_name(n) or "").startswith(("jax.lax.", "lax."))
                for n in ast.walk(src.tree)):
            continue
        axes = _declared_axes(src.tree)
        body_nodes: dict[int, str] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef) and id(node) in bodies:
                for sub in ast.walk(node):
                    body_nodes.setdefault(id(sub), node.name)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            leaf = name.split(".")[-1]
            if leaf in _COLLECTIVES and \
                    name.startswith(("jax.lax.", "lax.")):
                if id(node) not in body_nodes:
                    out.append(Finding(
                        "shardmap-collective", src.rel, node.lineno,
                        f"{name}() outside any shard_map body — its "
                        f"axis name is unbound on a real mesh"))
                lit = _axis_literal(node)
                if lit is not None and axes and lit not in axes:
                    out.append(Finding(
                        "shardmap-axis", src.rel, node.lineno,
                        f"{name}() names axis {lit!r}; the module "
                        f"declares {sorted(axes)}"))
            elif id(node) in body_nodes:
                where = body_nodes[id(node)]
                if leaf in _CALLBACKS and (
                        name.startswith(("jax.debug.", "debug."))
                        or leaf in ("io_callback", "pure_callback",
                                    "host_callback", "print",
                                    "breakpoint")):
                    out.append(Finding(
                        "shardmap-callback", src.rel, node.lineno,
                        f"shard_map body {where} invokes host "
                        f"callback {name}()"))
                elif leaf in _FETCHES and (
                        name.split(".")[0] in ("np", "numpy", "jax")
                        or leaf in ("item", "block_until_ready")):
                    out.append(Finding(
                        "shardmap-callback", src.rel, node.lineno,
                        f"shard_map body {where} fetches to host via "
                        f"{name}()"))
    return out
