"""JAX purity pass.

Functions handed to `jax.jit` / `jax.shard_map` (decorator or direct
call, including `partial(jax.jit, ...)`) execute as traced programs:
they run ONCE per shape specialization, then replay as compiled XLA.
Side effects silently freeze at trace time — a `time.time()` call
becomes a constant, a `random.random()` the same draw forever, a log
line fires once per compile, and mutation of closed-over Python state
happens at trace time only. `jax-impure` flags those inside any
jitted/shard_map'd function:

  * Python RNG / wall-clock / logging / print / file I/O calls;
  * `global` / `nonlocal` rebinding;
  * in-place mutation (`.append`/`.update`/subscript-store/attribute-
    store) of closed-over or `self` state.

`jax-donated-reuse` tracks the repo's donation idiom: a step built by
`compiled_encoded_step(..., donate_words=True)` DONATES its wire-buffer
argument — the device aliases its memory for the output, so the buffer
is dead the moment the call dispatches. Loading the same variable after
the donating call reads freed device memory (XLA raises at best,
corrupts at worst).
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import call_name, dotted

NAME = "purity"

RULES = {
    "jax-impure": (
        "function traced by jax.jit/shard_map calls RNG/time/logging/"
        "I-O or mutates closed-over state — the effect freezes at "
        "trace time instead of running per step"),
    "jax-donated-reuse": (
        "buffer passed to a donate_words=True compiled step is donated "
        "(device memory aliased to the output); using it after the "
        "call reads freed memory"),
}

_IMPURE_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.sleep",
    "time.time_ns", "datetime.now", "datetime.datetime.now",
    "print", "input", "open",
}
_IMPURE_PREFIX = ("random.", "np.random.", "numpy.random.",
                  "logging.", "log.", "logger.")
_MUTATORS = {"append", "extend", "insert", "update", "add", "pop",
             "popitem", "clear", "setdefault", "remove", "discard",
             "appendleft", "write"}


def _jitted_functions(tree: ast.Module):
    """Yield (FunctionDef, how) for functions compiled by jit/shard_map:
    decorated directly, via partial(jax.jit, ...), or passed by name to
    a jit/shard_map call anywhere in the module."""
    defs_by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, []).append(node)

    def is_jit_name(name: str | None) -> bool:
        return bool(name) and (name.split(".")[-1] in ("jit", "shard_map")
                               or name.endswith(".pjit"))

    seen: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = dotted(d)
                inner = None
                if isinstance(dec, ast.Call) and name and \
                        name.split(".")[-1] == "partial" and dec.args:
                    inner = dotted(dec.args[0])
                if is_jit_name(name) or is_jit_name(inner):
                    if id(node) not in seen:
                        seen.add(id(node))
                        yield node, "decorator"
        elif isinstance(node, ast.Call) and is_jit_name(call_name(node)):
            args = list(node.args)
            # jax.jit(shard_map(f, ...)) — unwrap nested compile calls
            while args and isinstance(args[0], ast.Call) \
                    and is_jit_name(call_name(args[0])):
                args = list(args[0].args)
            if args and isinstance(args[0], ast.Name):
                for fn in defs_by_name.get(args[0].id, ()):
                    if id(fn) not in seen:
                        seen.add(id(fn))
                        yield fn, "jit call"


def _local_names(fn: ast.FunctionDef) -> set[str]:
    out = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                           + fn.args.kwonlyargs)}
    if fn.args.vararg:
        out.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        out.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            t = node.target
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.comprehension):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                out.add(node.name)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for n in ast.walk(node.optional_vars):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def _scan_jitted(src, fn: ast.FunctionDef) -> list[Finding]:
    out: list[Finding] = []
    local = _local_names(fn)
    where = f"jitted fn {fn.name}"
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            out.append(Finding(
                "jax-impure", src.rel, node.lineno,
                f"{where} rebinds "
                f"{'/'.join(node.names)} via "
                f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
            ))
        elif isinstance(node, ast.Call):
            name = call_name(node) or ""
            if name in _IMPURE_CALLS or name.startswith(_IMPURE_PREFIX):
                out.append(Finding(
                    "jax-impure", src.rel, node.lineno,
                    f"{where} calls {name}() — effect freezes at "
                    f"trace time"))
            else:
                leaf = name.split(".")[-1]
                root = name.split(".")[0] if name else ""
                if (leaf in _MUTATORS and root
                        and root not in local and "." in name):
                    out.append(Finding(
                        "jax-impure", src.rel, node.lineno,
                        f"{where} mutates closed-over "
                        f"'{name.rsplit('.', 1)[0]}' via .{leaf}()"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute):
                    d = dotted(t) or t.attr
                    root = d.split(".")[0]
                    if root == "self" or root not in local:
                        out.append(Finding(
                            "jax-impure", src.rel, t.lineno,
                            f"{where} stores to closed-over "
                            f"attribute '{d}'"))
                elif isinstance(t, ast.Subscript):
                    d = dotted(t.value)
                    root = (d or "").split(".")[0]
                    if d and root not in local:
                        out.append(Finding(
                            "jax-impure", src.rel, t.lineno,
                            f"{where} stores into closed-over "
                            f"'{d}' by subscript"))
    return out


def _donation_findings(src) -> list[Finding]:
    """Per function: find `S = ...compiled_encoded_step(...,
    donate_words=True)`, then `S(..., buf)` — any load of `buf`'s
    expression after that call line is a use-after-donation."""
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        donating_steps: set[str] = set()
        donated: dict[str, int] = {}  # expr repr -> donation line
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                cn = call_name(stmt.value) or ""
                if cn.split(".")[-1] == "compiled_encoded_step" and any(
                        kw.arg == "donate_words"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in stmt.value.keywords):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            donating_steps.add(t.id)
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Call) and \
                    isinstance(stmt.func, ast.Name) and \
                    stmt.func.id in donating_steps and stmt.args:
                d = dotted(stmt.args[-1])
                if d:
                    # a multiline call's own args sit past .lineno; only
                    # loads past the call's END are uses-after-donation
                    donated[d] = stmt.end_lineno or stmt.lineno
        for d, line in donated.items():
            for sub in ast.walk(node):
                if (isinstance(sub, (ast.Name, ast.Attribute))
                        and isinstance(getattr(sub, "ctx", None), ast.Load)
                        and dotted(sub) == d and sub.lineno > line):
                    out.append(Finding(
                        "jax-donated-reuse", src.rel, sub.lineno,
                        f"'{d}' read after being donated to the "
                        f"compiled step at line {line}"))
    return out


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        for fn, _how in _jitted_functions(src.tree):
            out.extend(_scan_jitted(src, fn))
        out.extend(_donation_findings(src))
    return out
