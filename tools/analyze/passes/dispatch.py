"""Dispatch/fetch budget pass.

The hot-path invariants PRs 5-6 earned by hand — ONE fused kernel
dispatch and at most ONE device->host fetch per close cycle / join
micro-batch — are worth ~22x between kernel-only and end-to-end
throughput, and nothing structural keeps them: one stray `np.asarray`
in a drain or one per-window fetch loop silently reintroduces a round
trip per item. This pass makes the budgets declared and checked.

Contract comments bind a budget to a function, on the line directly
above its `def` (above any decorators) or on the `def` line itself:

    # contract: dispatches<=1 fetches<=1
    def _close_windows(self, starts): ...

`dispatch-budget` then checks the body statically:

  * a recognized dispatch (call to a compiled-kernel callable) or
    fetch (device->host sync) inside a `for`/`while` loop blows ANY
    finite budget — unless the loop is the sanctioned shape-group
    stacking idiom (iterating a `by_shape` grouping, which fetches
    once per compiled shape, the repo's batched-drain pattern);
  * the static call-site count (branch-aware: `if`/`else` arms take
    the max, early-returning arms split the tail) must fit the budget.

`dispatch-sync` flags device syncs in UNANNOTATED functions of the
kernel/executor layer: every legitimate drain point carries a contract
(which both sanctions and budgets it), so a bare sync is either a new
drain that needs a budget or a hot-path regression.

Recognition (local, per class/module — no whole-program analysis):

  dispatches  calls to names bound from kernel factories — `jax.jit`,
              `lattice.join_probe_insert/...step/_only`, `join_evict`,
              `compiled_encoded_step`, `self._count_close_kernel(...)`
              — directly, or via `self.X = <factory>` anywhere in the
              class, or via attributes of a `lattice.compiled(...)` /
              `ShardedLattice(...)` result (`self.X = fns.extract_...`).
  fetches     `.block_until_ready()`, `jax.device_get`, `.item()`, and
              `np.asarray(x)` where x is device-derived (assigned from
              a jnp./jax./kernel call) or named like a device value
              (packed/buf/words/state/dev/stacked...). Inside a
              contract function every bare `np.asarray(name)` without
              a dtype counts — contract paths are device paths.
"""

from __future__ import annotations

import ast
import re

from tools.analyze import Finding
from tools.analyze.passes import call_name, dotted

NAME = "dispatch"

RULES = {
    "dispatch-budget": (
        "function declaring `# contract: dispatches<=N fetches<=M` "
        "exceeds it statically — a kernel dispatch or device fetch in "
        "an unsanctioned loop, or more call sites than the budget"),
    "dispatch-sync": (
        "device->host sync in an unannotated kernel/executor-layer "
        "function — every sanctioned drain point declares a "
        "`# contract:` budget; a bare sync is a hot-path regression"),
    "dispatch-contract-syntax": (
        "unparseable `# contract:` comment — a typo here silently "
        "un-checks the budget"),
}

# the kernel/executor layer dispatch-sync polices (contract functions
# are budget-checked instead; everything else in the repo is host code
# where np.asarray is routine)
HOT_PATH_FILES = (
    "hstream_tpu/engine/lattice.py",
    "hstream_tpu/engine/executor.py",
    "hstream_tpu/engine/join.py",
    "hstream_tpu/engine/pipeline.py",
    "hstream_tpu/engine/session.py",
    "hstream_tpu/parallel/executor.py",
    "hstream_tpu/parallel/lattice.py",
    # the framed append path (ISSUE 12): host-only by contract — its
    # hot functions declare dispatches<=0 fetches<=0, and any device
    # sync creeping into the ingest door is a regression
    "hstream_tpu/common/colframe.py",
    "hstream_tpu/server/appendfront.py",
    # the traced-lock wrapper (ISSUE 14) sits inside every
    # instrumented drain path: a device sync creeping into acquire/
    # release would tax every critical section in the server
    "hstream_tpu/common/locktrace.py",
    # the device cost plane (ISSUE 18): HBM accounting runs at scrape
    # time against live executors and the device-time sampler sits
    # inside every kernel_family scope — each hook declares its budget
    # (the sampler's fence/measure are the ONLY sanctioned syncs)
    "hstream_tpu/stats/devicecost.py",
    # the read plane (ISSUE 20): serve_view sits on every pull query —
    # its budget is one extract dispatch + one fetch per cache miss,
    # and a bare sync creeping into the hit path would tax every reader
    "hstream_tpu/server/readcache.py",
)

# factories whose RESULT is a compiled kernel callable
KERNEL_FACTORIES = {
    "jit", "pjit", "shard_map",
    "join_probe_insert", "join_probe_only", "join_probe_insert_step",
    "join_evict", "compiled_encoded_step",
    "_count_close_kernel",
    "session_step_kernel", "session_merge_kernel",
    "session_extract_kernel", "session_remap_kernel",
}
# factories returning a NAMESPACE of kernels (attributes are kernels)
KERNEL_NAMESPACE_FACTORIES = {"compiled", "ShardedLattice",
                              "ShardedJoinLattice",
                              "ShardedSessionLattice"}

# device-value lexicon: identifier stems that name device arrays in
# this codebase (packed extract buffers, wire words, lattice state)
_DEVICE_NAME_RE = re.compile(
    r"(^|_)(packed|buf|bufs|words|state|dev|device|stacked)($|_|s$)")

_CONTRACT_RE = re.compile(r"#\s*contract:\s*(.+)$")
_BUDGET_RE = re.compile(r"^(dispatches|fetches)<=(\d+)$")

# loop-iterable source text marking the sanctioned shape-group
# stacking idiom (one fetch per compiled buffer shape)
_SHAPE_GROUP_TOKENS = ("by_shape",)

_FETCH_METHODS = {"block_until_ready", "item"}


def _parse_contract(text: str) -> dict[str, int] | None:
    """{'dispatches': N, 'fetches': M} (either optional) or None on a
    syntax error."""
    out: dict[str, int] = {}
    for tok in text.split():
        m = _BUDGET_RE.match(tok)
        if not m:
            return None
        out[m.group(1)] = int(m.group(2))
    return out or None


def _contract_of(src, fn: ast.FunctionDef):
    """(budgets, comment_line) for a contract bound to `fn`, or
    (None, line) on a malformed comment, or (None, None)."""
    # same-line comment on the def
    def_line = src.lines[fn.lineno - 1] if fn.lineno <= len(src.lines) \
        else ""
    m = _CONTRACT_RE.search(def_line)
    if m is not None:
        return _parse_contract(m.group(1)), fn.lineno
    # comment-only lines directly above the def / its decorators
    first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
    i = first - 1  # 1-based line above
    while i >= 1:
        line = src.lines[i - 1].strip()
        if not line.startswith("#"):
            break
        m = _CONTRACT_RE.search(line)
        if m is not None:
            return _parse_contract(m.group(1)), i
        i -= 1
    return None, None


def _class_kernel_attrs(cls: ast.ClassDef) -> set[str]:
    """self-attribute names assigned from kernel factories anywhere in
    the class (e.g. `self._extract_touched = fns.extract_touched` where
    `fns = lattice.compiled(...)`)."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.FunctionDef):
            continue
        ns_vars: set[str] = set()  # locals holding kernel namespaces
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign) or not stmt.targets:
                continue
            rhs = stmt.value
            leaf = (call_name(rhs) or "").split(".")[-1] \
                if isinstance(rhs, ast.Call) else None
            for t in stmt.targets:
                if isinstance(t, ast.Name) and \
                        leaf in KERNEL_NAMESPACE_FACTORIES:
                    ns_vars.add(t.id)
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if leaf in KERNEL_FACTORIES:
                    out.add(t.attr)
                elif leaf in KERNEL_NAMESPACE_FACTORIES:
                    ns_vars.add(f"self.{t.attr}")
                elif isinstance(rhs, ast.Attribute):
                    base = dotted(rhs.value)
                    if base in ns_vars:
                        out.add(t.attr)
        # second sweep: attributes of namespace vars found above
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign):
                continue
            rhs = stmt.value
            if not isinstance(rhs, ast.Attribute):
                continue
            base = dotted(rhs.value)
            if base not in ns_vars:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    out.add(t.attr)
    return out


def _local_kernel_names(fn: ast.FunctionDef) -> set[str]:
    """Local names bound from kernel factories inside `fn`
    (`kern = lattice.join_probe_insert(...)`, `step = jax.jit(...)`)."""
    out: set[str] = set()
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Call):
            leaf = (call_name(stmt.value) or "").split(".")[-1]
            if leaf in KERNEL_FACTORIES:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _device_locals(fn: ast.FunctionDef, kernels: set[str]) -> set[str]:
    """Local names assigned (incl. tuple-unpacked) from jnp./jax. calls
    or kernel-callable calls — device values by construction."""
    out: set[str] = set()

    def _is_device_call(v: ast.AST) -> bool:
        if not isinstance(v, ast.Call):
            return False
        name = call_name(v) or ""
        if name.startswith(("jnp.", "jax.")) and \
                not name.startswith("jax.profiler"):
            return True
        leaf = name.split(".")[-1]
        return leaf in kernels or name in kernels \
            or (name.startswith("self.") and leaf in kernels)

    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and _is_device_call(stmt.value):
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        # iterating a device container (state planes, staged buffers)
        # makes the loop/comprehension targets device values too
        elif isinstance(stmt, (ast.For, ast.comprehension)):
            if _mentions_device(stmt.iter, out):
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
    return out


def _mentions_device(node: ast.AST, device_locals: set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if sub.id in device_locals or \
                    _DEVICE_NAME_RE.search(sub.id):
                return True
        elif isinstance(sub, ast.Attribute):
            if _DEVICE_NAME_RE.search(sub.attr):
                return True
    return False


def _is_fetch(call: ast.Call, device_locals: set[str],
              in_contract: bool) -> bool:
    name = call_name(call) or ""
    leaf = name.split(".")[-1]
    if leaf in _FETCH_METHODS:
        return True
    if name in ("jax.device_get", "device_get"):
        return True
    if leaf == "asarray" and name.split(".")[0] in ("np", "numpy"):
        if any(kw.arg == "dtype" for kw in call.keywords) \
                or len(call.args) > 1:
            return False  # host-typed conversion, the repo's idiom
        if not call.args:
            return False
        arg = call.args[0]
        if isinstance(arg, (ast.List, ast.Tuple, ast.Constant)):
            return False  # literal -> host construction
        if in_contract:
            return True  # contract paths are device paths
        return _mentions_device(arg, device_locals)
    return False


def _is_dispatch(call: ast.Call, kernels: set[str],
                 local_kernels: set[str]) -> bool:
    name = call_name(call) or ""
    if not name:
        return False
    leaf = name.split(".")[-1]
    if isinstance(call.func, ast.Name):
        return leaf in local_kernels
    if name.startswith("self."):
        return leaf in kernels
    return False


class _Budget:
    """Branch-aware static (dispatches, fetches) counter that also
    reports loop violations."""

    def __init__(self, src, fn, kernels, local_kernels, device_locals,
                 in_contract):
        self.src = src
        self.fn = fn
        self.kernels = kernels
        self.local_kernels = local_kernels
        self.device_locals = device_locals
        self.in_contract = in_contract
        self.loop_findings: list[tuple[int, str, str]] = []

    def _expr_sites(self, node: ast.AST | None) -> tuple[int, int]:
        """(dispatches, fetches) in one expression subtree."""
        if node is None:
            return 0, 0
        d = f = 0
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if _is_dispatch(sub, self.kernels, self.local_kernels):
                d += 1
            elif _is_fetch(sub, self.device_locals, self.in_contract):
                f += 1
        return d, f

    def _stmt_sites(self, stmt: ast.stmt) -> tuple[int, int]:
        """(dispatches, fetches) in one statement's OWN expressions:
        compound statements contribute only their header (test / iter /
        with-items) — their bodies are counted recursively by count()."""
        if isinstance(stmt, (ast.If, ast.While)):
            return self._expr_sites(stmt.test)
        if isinstance(stmt, ast.For):
            return self._expr_sites(stmt.iter)
        if isinstance(stmt, ast.With):
            d = f = 0
            for item in stmt.items:
                d2, f2 = self._expr_sites(item.context_expr)
                d += d2
                f += f2
            return d, f
        if isinstance(stmt, (ast.Try, ast.FunctionDef)):
            return 0, 0
        return self._expr_sites(stmt)

    def _loop_sanctioned(self, loop) -> bool:
        if not isinstance(loop, ast.For):
            return False
        try:
            text = ast.unparse(loop.iter)
        except Exception:  # noqa: BLE001 — unparse is best-effort
            text = ""
        return any(tok in text for tok in _SHAPE_GROUP_TOKENS)

    def count(self, stmts: list[ast.stmt]) -> tuple[int, int]:
        if not stmts:
            return 0, 0
        head, rest = stmts[0], stmts[1:]
        hd, hf = self._stmt_sites(head)
        if isinstance(head, ast.If):
            bd, bf = self.count(head.body)
            od, of_ = self.count(head.orelse)

            def _terminates(body):
                return bool(body) and isinstance(
                    body[-1], (ast.Return, ast.Raise, ast.Continue,
                               ast.Break))

            rd, rf = self.count(rest)
            if _terminates(head.body):
                return (hd + max(bd, od + rd), hf + max(bf, of_ + rf))
            if _terminates(head.orelse):
                return (hd + max(od, bd + rd), hf + max(of_, bf + rf))
            return (hd + max(bd, od) + rd, hf + max(bf, of_) + rf)
        if isinstance(head, (ast.For, ast.While)):
            bd, bf = self.count(head.body)
            od, of_ = self.count(head.orelse)
            if (bd or bf) and not self._loop_sanctioned(head):
                kind = "dispatch" if bd else "fetch"
                try:
                    it = ast.unparse(head.iter) \
                        if isinstance(head, ast.For) else "while"
                except Exception:  # noqa: BLE001
                    it = "loop"
                self.loop_findings.append((head.lineno, kind, it))
            rd, rf = self.count(rest)
            return hd + bd + od + rd, hf + bf + of_ + rf
        if isinstance(head, (ast.With, ast.Try)):
            bodies = [head.body]
            if isinstance(head, ast.Try):
                bodies += [h.body for h in head.handlers]
                bodies += [head.orelse, head.finalbody]
            bd = bf = 0
            for b in bodies:
                d2, f2 = self.count(b)
                bd += d2
                bf += f2
            rd, rf = self.count(rest)
            return hd + bd + rd, hf + bf + rf
        if isinstance(head, ast.FunctionDef):
            rd, rf = self.count(rest)
            return rd, rf  # nested def: counted when IT is annotated
        rd, rf = self.count(rest)
        return hd + rd, hf + rf


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(d) or ""
        if name.split(".")[-1] in ("jit", "shard_map", "pjit"):
            return True
    return False


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        kernel_attrs: set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                kernel_attrs |= _class_kernel_attrs(node)
        hot = src.rel in HOT_PATH_FILES
        for fn in ast.walk(src.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            budgets, cline = _contract_of(src, fn)
            if cline is not None and budgets is None:
                out.append(Finding(
                    "dispatch-contract-syntax", src.rel, cline,
                    f"contract comment on {fn.name} does not parse — "
                    f"expected `# contract: dispatches<=N fetches<=M`"))
                continue
            local_kernels = _local_kernel_names(fn)
            device_locals = _device_locals(
                fn, kernel_attrs | local_kernels)
            if budgets is not None:
                b = _Budget(src, fn, kernel_attrs, local_kernels,
                            device_locals, in_contract=True)
                d, f = b.count(fn.body)
                for line, kind, it in b.loop_findings:
                    out.append(Finding(
                        "dispatch-budget", src.rel, line,
                        f"{fn.name}: {kind} inside a loop over {it} — "
                        f"the per-cycle budget cannot hold"))
                nd = budgets.get("dispatches")
                if nd is not None and d > nd:
                    out.append(Finding(
                        "dispatch-budget", src.rel, fn.lineno,
                        f"{fn.name}: {d} static dispatch site(s) "
                        f"exceed the declared dispatches<={nd}"))
                nf = budgets.get("fetches")
                if nf is not None and f > nf:
                    out.append(Finding(
                        "dispatch-budget", src.rel, fn.lineno,
                        f"{fn.name}: {f} static fetch site(s) exceed "
                        f"the declared fetches<={nf}"))
            elif hot and not _jit_decorated(fn):
                nested: set[int] = set()
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.FunctionDef) and sub is not fn:
                        for inner in ast.walk(sub):
                            nested.add(id(inner))
                for sub in ast.walk(fn):
                    if id(sub) in nested:
                        continue  # nested defs are their own scope
                    if isinstance(sub, ast.Call) and \
                            _is_fetch(sub, device_locals, False):
                        out.append(Finding(
                            "dispatch-sync", src.rel, sub.lineno,
                            f"{fn.name}: device sync "
                            f"{call_name(sub) or '<call>'}() without a "
                            f"`# contract:` budget — annotate the "
                            f"drain or move the sync off the hot path"))
    return out
