"""Error-contract pass: server-emitted gRPC statuses, the gateway's
HTTP mapping, and the client's retry classification must agree.

The emitted set is computed from the tree, both ways the server emits:

  * typed errors — every `HStreamError` subclass in common/errors.py
    (its `grpc_status`, resolved through the class hierarchy) that is
    actually `raise`d somewhere in production code (handlers catch
    HStreamError at the boundary and abort with that status);
  * explicit `context.abort(grpc.StatusCode.X, ...)` literals.

Contracts checked:

  err-http        every emitted status has an explicit HTTP mapping in
                  http_gateway's `_STATUS` table (500-by-default hides
                  contract drift: a new status silently becomes a 500);
  err-retry-class every emitted status is classified retryable or not
                  in client/retry.py (RETRYABLE_CODES ∪
                  NON_RETRYABLE_CODES);
  err-dead-retry  every status the client retries on is actually
                  emitted server-side (or is transport-generated:
                  UNAVAILABLE / DEADLINE_EXCEEDED / CANCELLED, which
                  the gRPC runtime raises without server code);
  err-hinted-*    the NOT_LEADER contract (ISSUE 9): an error class
                  that carries a ``leader_hint`` rides a status the
                  client follows ONLY when the hint is present
                  (HINTED_RETRYABLE_CODES). Three directions: every
                  hint-carrying class's status is in the hinted set
                  (else failover fails the statement instead of
                  following), every hinted code is emitted by some
                  hint-carrying class (no dead hint-follow paths), and
                  every hinted code stays in NON_RETRYABLE_CODES so
                  its BARE form — a mid-call transport drop that may
                  have landed a mutation — is never blanket-retried.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import dotted

NAME = "errcontract"

RULES = {
    "err-http": (
        "gRPC status emitted by the server has no explicit HTTP "
        "mapping in http_gateway._STATUS"),
    "err-retry-class": (
        "gRPC status emitted by the server is neither in "
        "client.retry.RETRYABLE_CODES nor NON_RETRYABLE_CODES"),
    "err-dead-retry": (
        "client retries a status code no server path emits "
        "(transport-generated codes are exempt)"),
    "err-hinted-unclassified": (
        "status emitted by a leader-hint-carrying error class is not "
        "in client.retry.HINTED_RETRYABLE_CODES (failover would fail "
        "the statement instead of following the hint)"),
    "err-dead-hint": (
        "HINTED_RETRYABLE_CODES contains a status no hint-carrying "
        "error class emits"),
    "err-hinted-bare": (
        "hinted-retryable status is missing from NON_RETRYABLE_CODES "
        "— its bare (hintless) form could be blanket-retried, which "
        "can double-apply a mutation landed by a mid-call drop"),
}

ERRORS_FILE = "hstream_tpu/common/errors.py"
GATEWAY_FILE = "hstream_tpu/http_gateway/__init__.py"
RETRY_FILE = "hstream_tpu/client/retry.py"

# codes the gRPC runtime itself produces; the client may retry them
# without any server-side abort existing
TRANSPORT_CODES = {"UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED"}


def _status_of(node: ast.AST) -> str | None:
    """'RESOURCE_EXHAUSTED' from a grpc.StatusCode.X expression."""
    d = dotted(node)
    if d and ".StatusCode." in f".{d}":
        return d.rsplit(".", 1)[1]
    return None


def _error_classes(tree: ast.Module) -> dict[str, str]:
    """class name -> resolved grpc status, following single-module
    inheritance; HStreamError defaults INTERNAL."""
    own: dict[str, str | None] = {}
    bases: dict[str, list[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        bases[node.name] = [b.id for b in node.bases
                            if isinstance(b, ast.Name)]
        status = None
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == "grpc_status":
                        status = _status_of(stmt.value)
        own[node.name] = status

    def resolve(name: str, depth: int = 0) -> str:
        if depth > 10 or name not in own:
            return "INTERNAL"
        if own[name]:
            return own[name]  # type: ignore[return-value]
        for b in bases.get(name, ()):
            if b in own:
                return resolve(b, depth + 1)
        return "INTERNAL"

    return {name: resolve(name) for name in own}


def _hint_classes(tree: ast.Module) -> set[str]:
    """Error classes that carry a leader hint: any method assigns
    ``self.leader_hint`` (the NOT_LEADER shape)."""
    out: set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Attribute)
                    and isinstance(sub.ctx, ast.Store)
                    and sub.attr == "leader_hint"
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                out.add(node.name)
                break
    return out


def _emitted(files, classes: dict[str, str], *,
             include_aborts: bool = True) -> dict[str, tuple[str, int]]:
    """status -> one representative (path, line) where it is emitted.
    `include_aborts=False` restricts to raises of `classes` (the
    hinted-contract check scopes emission to hint-carrying classes)."""
    out: dict[str, tuple[str, int]] = {}
    for src in files:
        if not src.rel.startswith("hstream_tpu/"):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = (dotted(exc.func) if isinstance(exc, ast.Call)
                        else dotted(exc))
                leaf = (name or "").split(".")[-1]
                if leaf in classes:
                    out.setdefault(classes[leaf], (src.rel, node.lineno))
            elif include_aborts and isinstance(node, ast.Call):
                cn = dotted(node.func) or ""
                if cn.endswith(".abort") and node.args:
                    st = _status_of(node.args[0])
                    if st is not None:
                        out.setdefault(st, (src.rel, node.lineno))
    return out


def _gateway_map(src) -> tuple[set[str], int]:
    codes: set[str] = set()
    line = 1
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_STATUS"
                for t in node.targets):
            line = node.lineno
            if isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    st = _status_of(k) if k is not None else None
                    if st:
                        codes.add(st)
    return codes, line


def _retry_sets(src) -> tuple[dict[str, set[str]], int]:
    out: dict[str, set[str]] = {"RETRYABLE_CODES": set(),
                                "NON_RETRYABLE_CODES": set(),
                                "HINTED_RETRYABLE_CODES": set()}
    line = 1
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in out:
                    line = node.lineno
                    for sub in ast.walk(node.value):
                        st = _status_of(sub)
                        if st:
                            out[t.id].add(st)
    return out, line


# NOTE: messages are baseline keys (rule, path, message) — they name the
# emitting FILE but never a line number, so unrelated edits shifting a
# line cannot resurrect a grandfathered finding.


def run(files, repo) -> list[Finding]:
    by_rel = {f.rel: f for f in files}
    errors = by_rel.get(ERRORS_FILE)
    gateway = by_rel.get(GATEWAY_FILE)
    retry = by_rel.get(RETRY_FILE)
    if errors is None or gateway is None or retry is None:
        return []  # fixture runs without the real tree
    classes = _error_classes(errors.tree)
    emitted = _emitted(files, classes)
    http_codes, http_line = _gateway_map(gateway)
    retry_sets, retry_line = _retry_sets(retry)
    classified = retry_sets["RETRYABLE_CODES"] \
        | retry_sets["NON_RETRYABLE_CODES"]

    out: list[Finding] = []
    for st, (path, _line) in sorted(emitted.items()):
        if st not in http_codes:
            out.append(Finding(
                "err-http", GATEWAY_FILE, http_line,
                f"status {st} (emitted in {path}) has no "
                f"HTTP mapping in _STATUS"))
        if st not in classified:
            out.append(Finding(
                "err-retry-class", RETRY_FILE, retry_line,
                f"status {st} (emitted in {path}) is not "
                f"classified retryable/non-retryable"))
    for st in sorted(retry_sets["RETRYABLE_CODES"]):
        if st not in emitted and st not in TRANSPORT_CODES:
            out.append(Finding(
                "err-dead-retry", RETRY_FILE, retry_line,
                f"client retries {st} but no server path emits it"))
    # the NOT_LEADER hinted contract (ISSUE 9): statuses followable
    # only WITH a leader hint agree with the hint-carrying classes
    hinted = retry_sets["HINTED_RETRYABLE_CODES"]
    hint_emitted = _emitted(
        files, {c: s for c, s in classes.items()
                if c in _hint_classes(errors.tree)},
        include_aborts=False)
    for st, (path, _line) in sorted(hint_emitted.items()):
        if st not in hinted:
            out.append(Finding(
                "err-hinted-unclassified", RETRY_FILE, retry_line,
                f"status {st} (hint-carrying, emitted in {path}) is "
                f"not in HINTED_RETRYABLE_CODES"))
    for st in sorted(hinted):
        if st not in hint_emitted:
            out.append(Finding(
                "err-dead-hint", RETRY_FILE, retry_line,
                f"client follows hints on {st} but no hint-carrying "
                f"error class emits it"))
        if st not in retry_sets["NON_RETRYABLE_CODES"]:
            out.append(Finding(
                "err-hinted-bare", RETRY_FILE, retry_line,
                f"hinted status {st} must stay in NON_RETRYABLE_CODES "
                f"(bare form may follow a landed mutation)"))
    return out
