"""Blocking-in-hot-path pass.

Hot contexts (inferred from the tree's own structure, not a hand list):

  * gRPC handler methods — PascalCase methods of classes whose name
    ends in `Servicer` or `Service` (the wire surface; a blocked
    handler pins one of the server's worker threads);
  * the Prometheus scrape path — every top-level function of
    `hstream_tpu/stats/prometheus.py` (scrapes run on monitoring
    cadence and must stay O(live subsystems));
  * worker loops — `run()` methods of `threading.Thread` subclasses
    and any function named `*_loop` (they own a latency budget per
    tick; an unbounded block stalls the whole pipeline stage).

Flagged inside a hot context (`blocking-hot`):

  * `time.sleep(...)` — poll with a timed Event.wait instead;
  * `subprocess.*` / `os.system` / `os.popen`;
  * file/dir I/O: builtin `open`, `os.walk`, `os.scandir`,
    `os.listdir`, `os.path.getsize`, `shutil.*`;
  * socket construction/connect;
  * unbounded waits: `.acquire()`, `.join()`, `.result()`, `.get()`,
    `.put()`, `.wait()` with no timeout argument.

Nested `def`s inside a hot function are skipped — they execute on
other threads (callbacks, drain threads) with their own context.

Carve-out (ISSUE 8): ``time.sleep`` inside methods of classes whose
name ends in ``Supervisor`` is sanctioned — a supervisor's dedicated
restart thread OWNS its latency budget; backoff sleeps between restart
attempts are the mechanism, not a stall. Every other blocking call in
a supervisor is still flagged.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import call_name, has_timeout, walk_classes

NAME = "blocking"

RULES = {
    "blocking-hot": (
        "blocking call (sleep / subprocess / file I/O / unbounded "
        "acquire-join-result-get-wait) inside a gRPC handler, the "
        "Prometheus scrape path, or a worker loop"),
}

_SCRAPE_FILE = "hstream_tpu/stats/prometheus.py"

# dotted-call suffixes that block outright
_HARD_BLOCK = {
    "time.sleep": "time.sleep",
    "os.system": "os.system",
    "os.popen": "os.popen",
    "os.walk": "directory walk",
    "os.scandir": "directory scan",
    "os.listdir": "directory listing",
    "os.path.getsize": "file stat",
    "socket.create_connection": "socket connect",
}
_HARD_PREFIX = ("subprocess.", "shutil.")

# method names that block unless a timeout bounds them; value = how
# many positional args imply a bound (Event.wait(0.5) -> 1)
_UNBOUNDED = {"acquire": 1, "join": 1, "result": 1, "get": 1, "put": 2,
              "wait": 1}
# receivers whose .get/.put/.join are not queue/thread waits
_SAFE_RECV_SUFFIX = (".headers", ".environ", "os.environ", "kwargs",
                     "args")


def _thread_subclasses(files) -> set[tuple[str, str]]:
    """(rel, class name) of every threading.Thread subclass."""
    out = set()
    for src in files:
        for cls in walk_classes(src.tree):
            for base in cls.bases:
                name = (base.attr if isinstance(base, ast.Attribute)
                        else base.id if isinstance(base, ast.Name)
                        else "")
                if name == "Thread":
                    out.add((src.rel, cls.name))
    return out


def _hot_functions(src, thread_classes):
    """Yield (fn, why, allow_sleep) for every hot context in one
    file; allow_sleep marks supervisor backoff threads (carve-out)."""
    if src.rel == _SCRAPE_FILE:
        for node in src.tree.body:
            if isinstance(node, ast.FunctionDef):
                yield node, "prometheus scrape path", False
    for cls in walk_classes(src.tree):
        servicer = cls.name.endswith(("Servicer", "Service"))
        threaded = (src.rel, cls.name) in thread_classes
        # supervisor restart threads own their latency budget: backoff
        # sleeps between restart attempts are sanctioned (ISSUE 8)
        supervisor = cls.name.endswith("Supervisor")
        for node in cls.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if servicer and node.name[:1].isupper():
                yield node, f"gRPC handler {cls.name}.{node.name}", False
            elif threaded and node.name == "run":
                yield node, f"worker loop {cls.name}.run", supervisor
            elif node.name.endswith("_loop"):
                yield (node, f"worker loop {cls.name}.{node.name}",
                       supervisor)
    for node in src.tree.body:
        if isinstance(node, ast.FunctionDef) \
                and node.name.endswith("_loop") and src.rel != _SCRAPE_FILE:
            yield node, f"worker loop {node.name}", False


class _BlockScan(ast.NodeVisitor):
    def __init__(self, src, why: str, allow_sleep: bool = False):
        self.src = src
        self.why = why
        self.allow_sleep = allow_sleep
        self.findings: list[Finding] = []

    def visit_FunctionDef(self, node):  # noqa: N802 — other threads
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node: ast.Call):  # noqa: N802
        name = call_name(node) or ""
        leaf = name.split(".")[-1]
        hit: str | None = None
        if name == "time.sleep" and self.allow_sleep:
            hit = None  # supervisor backoff carve-out (ISSUE 8)
        elif name in _HARD_BLOCK:
            hit = _HARD_BLOCK[name]
        elif name.startswith(_HARD_PREFIX):
            hit = name
        elif name == "open" or name.endswith(".open"):
            hit = "file open"
        elif leaf in _UNBOUNDED and "." in name:
            # string ``sep.join`` literals never parse as dotted Name
            # chains (dotted() needs a Name root), so only real waits
            # reach this branch
            recv = name.rsplit(".", 1)[0]
            if (not has_timeout(node, _UNBOUNDED[leaf])
                    and not recv.endswith(_SAFE_RECV_SUFFIX)):
                hit = f"unbounded {leaf}()"
        if hit is not None:
            self.findings.append(Finding(
                "blocking-hot", self.src.rel, node.lineno,
                f"{hit} via {name or leaf}(...) in {self.why}"))
        self.generic_visit(node)


def run(files, repo) -> list[Finding]:
    thread_classes = _thread_subclasses(files)
    out: list[Finding] = []
    for src in files:
        seen: set[int] = set()
        for fn, why, allow_sleep in _hot_functions(src, thread_classes):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            scan = _BlockScan(src, why, allow_sleep)
            for stmt in fn.body:
                scan.visit(stmt)
            out.extend(scan.findings)
    return out
