"""Analysis passes. Each module exports NAME (pass id), RULES
(rule id -> one-line doc), and run(files, repo) -> list[Finding]."""

from __future__ import annotations

import ast

# ---- shared AST helpers ----------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    """Dotted name of a call target ('time.sleep', 'self._in.get')."""
    return dotted(call.func)


def has_timeout(call: ast.Call, min_positional: int) -> bool:
    """True when the call passes a bound: a `timeout=`/`wait=False`
    keyword or at least `min_positional` positional args (e.g.
    Event.wait(0.5), Thread.join(5))."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return True
        if kw.arg == "wait":  # ThreadPoolExecutor.shutdown(wait=False)
            return isinstance(kw.value, ast.Constant) \
                and kw.value.value is False
    return len(call.args) >= min_positional


def class_methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_classes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node
