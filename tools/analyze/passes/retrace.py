"""Retrace discipline pass.

An XLA executable is keyed by (function identity, argument shapes,
static-arg values). The repo keeps steady-state recompiles at ZERO with
two idioms: kernel factories are memoized (`functools.lru_cache` on
`lattice.compiled` / `compiled_encoded_step` / the `join_*` factories,
`build_*` constructors called only from them) and every shape-bearing
argument is padded to a sticky power of two (`_stage_cap`, `_dev_bcap`,
`_pad_slots`) so varying batch/cycle widths converge on a few compiled
programs. This pass flags the ways that discipline silently breaks:

  retrace-uncached-jit  a `jax.jit`/`shard_map` wrapper constructed
                        inside a plain function (a per-call path): each
                        call builds a FRESH wrapper whose cache is
                        itself, so every invocation retraces. The
                        sanctioned shapes are lru_cache-decorated
                        factories, `build_*`/`mk_*`/`_build*`
                        constructors, `_compile`, and `__init__`.
  retrace-traced-branch a Python `if`/`while` on a traced argument
                        inside a jitted function — either a TracerBool
                        error or, with static args, a retrace per
                        distinct value (`x is None` tests are exempt:
                        None never traces).
  retrace-static-arg    `static_argnums`/`static_argnames` naming a
                        parameter whose default/annotation is a float,
                        list, or dict — floats retrace per distinct
                        value, unhashables TypeError at call time.
  retrace-shape-key     a memoized kernel factory called with a raw
                        `len(<batch-like>)` — unpadded shape keys
                        compile one executable per distinct size;
                        route through round_up_pow2 / the sticky-cap
                        helpers.
"""

from __future__ import annotations

import ast

from tools.analyze import Finding
from tools.analyze.passes import call_name, dotted
from tools.analyze.passes.purity import _jitted_functions

NAME = "retrace"

RULES = {
    "retrace-uncached-jit": (
        "jax.jit/shard_map wrapper constructed inside a per-call "
        "function — each call builds a fresh wrapper and retraces; "
        "memoize via an lru_cache factory (the build_* idiom)"),
    "retrace-traced-branch": (
        "Python if/while on a traced argument inside a jitted "
        "function — TracerBool error or a retrace per value; use "
        "jnp.where/lax.cond"),
    "retrace-static-arg": (
        "static_argnums/static_argnames targets a float/list/dict "
        "parameter — float statics retrace per distinct value, "
        "unhashables TypeError"),
    "retrace-shape-key": (
        "memoized kernel factory called with a raw len() of a batch "
        "value — unpadded shape keys defeat the pow2-padding compile "
        "cache"),
}

_SANCTIONED_PREFIXES = ("build_", "_build", "mk_")
_SANCTIONED_NAMES = {"_compile", "__init__", "compiled"}

# in-tree memoized kernel factories (by leaf name) whose arguments are
# compile-cache keys; module-local lru_cache'd defs are added per file
_KNOWN_FACTORIES = {
    "join_probe_insert", "join_probe_only", "join_probe_insert_step",
    "join_evict", "compiled_encoded_step", "compiled",
    "session_step_kernel", "session_merge_kernel",
    "session_extract_kernel", "session_remap_kernel",
}

_BATCHISH = ("batch", "batches", "rows", "codes", "kids", "matches",
             "keys", "vals", "records", "ts")


def _is_jit_name(name: str | None) -> bool:
    return bool(name) and name.split(".")[-1] in ("jit", "shard_map",
                                                  "pjit")


def _is_cached_factory_def(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = (dotted(d) or "").split(".")[-1]
        if name in ("lru_cache", "cache"):
            return True
    return False


def _sanctioned(fn: ast.FunctionDef) -> bool:
    if _is_cached_factory_def(fn):
        return True
    if fn.name in _SANCTIONED_NAMES:
        return True
    return fn.name.startswith(_SANCTIONED_PREFIXES)


def _enclosers(tree: ast.Module) -> dict[int, list[ast.FunctionDef]]:
    """node id -> chain of enclosing FunctionDefs (outermost first)."""
    out: dict[int, list[ast.FunctionDef]] = {}

    def visit(node: ast.AST, chain: list[ast.FunctionDef]) -> None:
        for child in ast.iter_child_nodes(node):
            nxt = chain
            if isinstance(child, ast.FunctionDef):
                out[id(child)] = chain
                nxt = chain + [child]
            else:
                out[id(child)] = chain
            visit(child, nxt)

    visit(tree, [])
    return out


def _uncached_jit(src) -> list[Finding]:
    out: list[Finding] = []
    chains = _enclosers(src.tree)
    for node in ast.walk(src.tree):
        site = None
        what = None
        chain = None
        if isinstance(node, ast.Call) and _is_jit_name(call_name(node)):
            site, what = node, call_name(node)
            chain = chains.get(id(node), [])
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                if _is_jit_name(dotted(d)):
                    site, what = dec, f"@{dotted(d)} {node.name}"
                    # the decorated def's own chain: its ENCLOSERS,
                    # not itself
                    chain = chains.get(id(node), [])
        if site is None:
            continue
        if not chain:
            continue  # module level: compiled once per import
        if any(_sanctioned(fn) for fn in chain):
            continue
        out.append(Finding(
            "retrace-uncached-jit", src.rel, site.lineno,
            f"{what} constructed inside {chain[-1].name}() — a "
            f"per-call wrapper retraces every invocation; memoize "
            f"via an lru_cache factory"))
    return out


def _traced_branches(src) -> list[Finding]:
    out: list[Finding] = []
    for fn, _how in _jitted_functions(src.tree):
        params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                                  + fn.args.kwonlyargs)}
        params.discard("self")
        nested: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                for inner in ast.walk(node):
                    nested.add(id(inner))
        for node in ast.walk(fn):
            if id(node) in nested:
                continue  # nested defs: separate trace scopes
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test = node.test
            # `x is (not) None` never traces (None is a static default)
            if isinstance(test, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
                continue
            hit = None
            for sub in ast.walk(test):
                if isinstance(sub, ast.Call):
                    leaf = (call_name(sub) or "").split(".")[-1]
                    if leaf == "isinstance":
                        hit = None
                        break
                if isinstance(sub, ast.Name) and sub.id in params:
                    hit = sub.id
            if hit:
                out.append(Finding(
                    "retrace-traced-branch", src.rel, node.lineno,
                    f"jitted fn {fn.name} branches on traced argument "
                    f"'{hit}' with Python "
                    f"{'if' if isinstance(node, ast.If) else 'while'}"))
    return out


def _static_args(src) -> list[Finding]:
    """jit(f, static_argnums/names=...) where the named param of `f`
    (resolved by name in the same module) defaults to / is annotated as
    float/list/dict."""
    out: list[Finding] = []
    defs = {n.name: n for n in ast.walk(src.tree)
            if isinstance(n, ast.FunctionDef)}

    def _bad_param(fn: ast.FunctionDef, idx: int | None,
                   pname: str | None):
        args = fn.args.posonlyargs + fn.args.args
        a = None
        if pname is not None:
            a = next((x for x in args if x.arg == pname), None)
        elif idx is not None and idx < len(args):
            a = args[idx]
        if a is None:
            return None
        ann = getattr(a, "annotation", None)
        if ann is not None:
            t = (dotted(ann) or "").split(".")[-1]
            if t in ("float", "list", "dict", "set"):
                return a.arg, t
        defaults = fn.args.defaults
        pos = args.index(a) - (len(args) - len(defaults))
        if 0 <= pos < len(defaults):
            d = defaults[pos]
            if isinstance(d, ast.Constant) and isinstance(d.value, float):
                return a.arg, "float"
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                return a.arg, type(d).__name__.lower()
        return None

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call) or \
                not _is_jit_name(call_name(node)):
            continue
        target = None
        if node.args and isinstance(node.args[0], ast.Name):
            target = defs.get(node.args[0].id)
        if target is None:
            continue
        for kw in node.keywords:
            hits = []
            if kw.arg == "static_argnums":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) and \
                            isinstance(v.value, int):
                        hits.append(_bad_param(target, v.value, None))
            elif kw.arg == "static_argnames":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) and \
                            isinstance(v.value, str):
                        hits.append(_bad_param(target, None, v.value))
            for hit in hits:
                if hit:
                    pname, t = hit
                    out.append(Finding(
                        "retrace-static-arg", src.rel, node.lineno,
                        f"static arg '{pname}' of {target.name} is "
                        f"{t}-typed — retraces per value / "
                        f"unhashable"))
    return out


def _shape_keys(src) -> list[Finding]:
    factories = set(_KNOWN_FACTORIES)
    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef) and \
                _is_cached_factory_def(node):
            factories.add(node.name)
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = (call_name(node) or "").split(".")[-1]
        if leaf not in factories:
            continue
        for arg in node.args:
            if not (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id == "len" and arg.args):
                continue
            inner = arg.args[0]
            name = (dotted(inner) or "").split(".")[-1].lower()
            if any(tok == name or name.endswith("_" + tok)
                   for tok in _BATCHISH):
                out.append(Finding(
                    "retrace-shape-key", src.rel, arg.lineno,
                    f"{leaf}(... len({dotted(inner)}) ...) keys the "
                    f"compile cache on a raw size — pad via "
                    f"round_up_pow2 / a sticky cap"))
    return out


def run(files, repo) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        out.extend(_uncached_jit(src))
        out.extend(_traced_branches(src))
        out.extend(_static_args(src))
        out.extend(_shape_keys(src))
    return out
