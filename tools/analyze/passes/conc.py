"""Shared whole-program concurrency model (ISSUE 14).

The per-class lock inference of the `locks` pass cannot see the bug
classes that killed availability in the chaos scenarios: lock-order
cycles that span OBJECTS (task holds its state lock and calls into the
supervisor, whose lock some other path takes first), check-then-act
races where a guarded read's decision outlives the critical section,
and waiting-while-holding. All three need one thing the per-class
passes don't build: a program-wide index of who owns which locks and
which callee a dotted call lands in.

This module builds that index once per analyzer run:

  * every class's lock attributes (same recognition as the locks pass:
    `with self.X:` on lock-ish names, or attrs assigned
    Lock/RLock/Condition/Semaphore — plus LISTS of locks, the
    append-front lane-lock shape, recognized by construction);
  * condition aliases: `self.C = threading.Condition(self.L)` means
    acquiring C IS acquiring L — the graph must not split one mutex
    into two nodes;
  * attribute/variable types from constructor calls
    (`self.front = AppendFront(...)`) so `self.front.submit()` resolves
    to a FunctionDef; a one-entry name lexicon types the repo's
    pervasive `ctx` convention (ServerContext), and an attribute whose
    type no constructor names falls back to the unique program-wide
    owner of that attribute name;
  * module-level locks (`_lock = threading.Lock()` globals);
  * per-function transitive lock-acquisition summaries (fixpoint over
    the resolvable call graph) — the callee-closure idea of the
    shardmap/overflow passes, lifted from one module to the program.

Lock node identity is the CLASS-scoped name (`QueryTask.state_lock`,
`jsondec:_lock` for module globals): all instances of a class share a
node, the lockdep "lock class" discipline — an order inversion between
two instances of the same class is real, but modeling it needs
instance identity no static pass has, so same-node edges are skipped.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from tools.analyze.passes import call_name, dotted

# attribute names that look like locks (the locks pass's convention)
LOCKISH = re.compile(r"(^|_)(lock|cond|cv|mutex|mutate)$|_lock$|_cv$")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "TracedLock"}
# named-lock factories from common/locktrace (lowercase, so outside
# the CamelCase constructor convention): full dotted names
_LOCK_FACTORIES = {"locktrace.lock", "locktrace.rlock"}
_LOCK_LIST_FACTORIES = {"locktrace.lock_list"}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_THREAD_CTORS = {"Thread", "Timer"}

# the repo's pervasive parameter/attribute naming conventions, used as
# a typing fallback exactly like the dispatch pass's device-name
# lexicon: `ctx` is always the ServerContext
NAME_TYPE_LEXICON = {"ctx": "ServerContext"}


def _ctor_leaf(value: ast.AST) -> str | None:
    """'AppendFront' for `AppendFront(...)` / `mod.AppendFront(...)`;
    None for anything that is not a plain constructor-looking call."""
    if not isinstance(value, ast.Call):
        return None
    name = call_name(value)
    if not name:
        return None
    leaf = name.split(".")[-1]
    # constructors are CamelCase by convention; a lowercase call is a
    # factory whose return type we cannot name
    return leaf if leaf[:1].isupper() else None


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = call_name(value) or ""
    return name.split(".")[-1] in _LOCK_CTORS \
        or name in _LOCK_FACTORIES


def _is_lock_list(value: ast.AST) -> bool:
    """`[threading.Lock() for ...]` / `[Lock(), Lock()]` /
    `locktrace.lock_list(...)` — a lock FAMILY (the append-front
    lane-lock shape)."""
    if isinstance(value, ast.Call) and \
            (call_name(value) or "") in _LOCK_LIST_FACTORIES:
        return True
    elts: list[ast.AST] = []
    if isinstance(value, ast.ListComp):
        elts = [value.elt]
    elif isinstance(value, (ast.List, ast.Tuple)):
        elts = list(value.elts)
    return bool(elts) and all(_is_lock_ctor(e) for e in elts)


@dataclass
class ClassInfo:
    name: str
    rel: str                      # module file, repo-relative
    node: ast.ClassDef = None     # type: ignore[assignment]
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    lock_attrs: set[str] = field(default_factory=set)
    lock_list_attrs: set[str] = field(default_factory=set)
    cond_alias: dict[str, str] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)
    queue_attrs: set[str] = field(default_factory=set)
    thread_attrs: set[str] = field(default_factory=set)

    def lock_node(self, attr: str) -> str:
        """Graph node for `self.<attr>` (condition aliases collapse
        onto the lock they wrap; lock lists get a `[]` family node)."""
        attr = self.cond_alias.get(attr, attr)
        if attr in self.lock_list_attrs:
            return f"{self.name}.{attr}[]"
        return f"{self.name}.{attr}"


@dataclass
class Program:
    classes: list[ClassInfo] = field(default_factory=list)
    by_name: dict[str, list[ClassInfo]] = field(default_factory=dict)
    module_funcs: dict[str, dict[str, ast.FunctionDef]] = \
        field(default_factory=dict)
    module_locks: dict[str, set[str]] = field(default_factory=dict)
    # (id of FunctionDef) -> transitive set of lock nodes it acquires
    acquires: dict[int, set[str]] = field(default_factory=dict)
    # (id of FunctionDef) -> owning ClassInfo / module rel
    fn_class: dict[int, ClassInfo | None] = field(default_factory=dict)
    fn_module: dict[int, str] = field(default_factory=dict)
    # attr name -> ctor types assigned to it ANYWHERE in the program
    # (self.X = Ctor(...) in classes, plus cross-object `obj.X = t`
    # where t's constructor is known — handlers wiring `mat.task =
    # task` is what types views' `task` attribute)
    global_attr_types: dict[str, set[str]] = field(default_factory=dict)

    def class_named(self, name: str) -> ClassInfo | None:
        cands = self.by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def attr_type(self, cls: ClassInfo | None, attr: str) -> str | None:
        """Type of `.attr` on an instance of `cls` — the class's own
        constructor assignment first, then the unique program-wide
        owner of that attribute name (so `ctx.supervisor` types even
        when `ctx` reached us untyped)."""
        if cls is not None:
            t = cls.attr_types.get(attr)
            if t is not None:
                return t
        types = self.global_attr_types.get(attr, set())
        return next(iter(types)) if len(types) == 1 else None


def _module_stem(rel: str) -> str:
    return os.path.basename(rel).rsplit(".", 1)[0]


def _scan_class(cls: ast.ClassDef, rel: str) -> ClassInfo:
    info = ClassInfo(name=cls.name, rel=rel, node=cls)
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[node.name] = node
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                d = dotted(item.context_expr)
                if d and d.startswith("self.") and d.count(".") == 1:
                    attr = d.split(".", 1)[1]
                    if LOCKISH.search(attr):
                        info.lock_attrs.add(attr)
        elif isinstance(node, ast.Assign):
            v = node.value
            leaf = _ctor_leaf(v)
            for tgt in node.targets:
                d = dotted(tgt)
                if not (d and d.startswith("self.")
                        and d.count(".") == 1):
                    continue
                attr = d.split(".", 1)[1]
                if _is_lock_ctor(v):
                    info.lock_attrs.add(attr)
                    if leaf == "Condition" and v.args:
                        wrapped = dotted(v.args[0])
                        if wrapped and wrapped.startswith("self."):
                            info.cond_alias[attr] = \
                                wrapped.split(".", 1)[1]
                elif leaf in _QUEUE_CTORS:
                    info.queue_attrs.add(attr)
                elif leaf in _THREAD_CTORS:
                    info.thread_attrs.add(attr)
                elif leaf is not None:
                    info.attr_types[attr] = leaf
                if _is_lock_list(v):
                    info.lock_attrs.add(attr)
                    info.lock_list_attrs.add(attr)
    return info


def _scan_module_locks(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            if _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


# one-entry memo: lockorder and waitholding both build the model for
# the same file list in one analyzer run, and the fixpoint acquire
# summaries are the expensive half of either pass. Keyed on the file
# OBJECT identities; the cached entry holds strong references to
# those objects, so their ids cannot be recycled while the key is
# comparable (a different list of different SourceFiles misses).
_memo: dict = {"key": None, "files": None, "prog": None}


def build_program(files) -> Program:
    key = tuple(id(f) for f in files)
    if _memo["key"] == key:
        return _memo["prog"]
    prog = _build_program(files)
    _memo.update(key=key, files=list(files), prog=prog)
    return prog


def _build_program(files) -> Program:
    prog = Program()
    for src in files:
        prog.module_locks[src.rel] = _scan_module_locks(src.tree)
        funcs: dict[str, ast.FunctionDef] = {}
        for node in src.tree.body:
            if isinstance(node, ast.FunctionDef):
                funcs[node.name] = node
                prog.fn_class[id(node)] = None
                prog.fn_module[id(node)] = src.rel
        prog.module_funcs[src.rel] = funcs
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                info = _scan_class(node, src.rel)
                prog.classes.append(info)
                prog.by_name.setdefault(node.name, []).append(info)
                for m in info.methods.values():
                    prog.fn_class[id(m)] = info
                    prog.fn_module[id(m)] = src.rel
    for info in prog.classes:
        for attr, t in info.attr_types.items():
            prog.global_attr_types.setdefault(attr, set()).add(t)
    # cross-object wiring: `obj.attr = t` where t was constructed in
    # the same function (`task = QueryTask(...); mat.task = task`)
    for src in files:
        for fn in ast.walk(src.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            ctor_locals = local_ctor_types(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                vtype = _ctor_leaf(node.value)
                if vtype is None and isinstance(node.value, ast.Name):
                    vtype = ctor_locals.get(node.value.id)
                if vtype is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            not (isinstance(t.value, ast.Name)
                                 and t.value.id == "self"):
                        prog.global_attr_types.setdefault(
                            t.attr, set()).add(vtype)
    _compute_acquires(prog)
    return prog


# ---- lock nodes held/acquired in one function -------------------------------


def chain_class(tokens: list[str], cls: ClassInfo | None,
                prog: Program,
                local_types: dict[str, str] | None) -> ClassInfo | None:
    """ClassInfo an attribute chain lands on (`self.a.b` -> type of b;
    `task` -> local/lexicon type), or None when a hop breaks."""
    if not tokens:
        return None
    head = tokens[0]
    if head == "self":
        cur = cls
    else:
        tname = (local_types or {}).get(head) \
            or NAME_TYPE_LEXICON.get(head)
        cur = prog.class_named(tname) if tname else None
        if cur is None and len(tokens) == 1:
            return None
    for tok in tokens[1:]:
        if cur is None and tok in NAME_TYPE_LEXICON:
            cur = prog.class_named(NAME_TYPE_LEXICON[tok])
            continue
        tname = prog.attr_type(cur, tok)
        cur = prog.class_named(tname) if tname else None
        if cur is None:
            return None
    return cur


def with_lock_node(item_expr: ast.AST, cls: ClassInfo | None,
                   module_rel: str, prog: Program,
                   local_types: dict[str, str] | None = None
                   ) -> str | None:
    """Graph node for one `with <expr>:` item, or None when the
    context manager is not a recognized lock. Resolves `self.X`,
    module globals, lock-family members (`self._lane_locks[i]`), and
    typed chains (`task.state_lock` where `task` types to a class
    owning that lock)."""
    d = dotted(item_expr)
    if d:
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2 and cls is not None:
            attr = parts[1]
            if attr in cls.lock_attrs:
                return cls.lock_node(attr)
            return None
        if len(parts) == 1 and \
                d in prog.module_locks.get(module_rel, ()):
            return f"{_module_stem(module_rel)}:{d}"
        if len(parts) >= 2:
            owner = chain_class(parts[:-1], cls, prog, local_types)
            if owner is not None and parts[-1] in owner.lock_attrs:
                return owner.lock_node(parts[-1])
        return None
    # `with self._lane_locks[i]:` — a member of a lock family
    if isinstance(item_expr, ast.Subscript):
        base = dotted(item_expr.value)
        if base and base.startswith("self.") and base.count(".") == 1 \
                and cls is not None:
            attr = base.split(".", 1)[1]
            if attr in cls.lock_list_attrs:
                return cls.lock_node(attr)
    return None


def direct_acquires(fn: ast.FunctionDef, cls: ClassInfo | None,
                    module_rel: str, prog: Program,
                    local_types: dict[str, str] | None = None
                    ) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                n = with_lock_node(item.context_expr, cls, module_rel,
                                   prog, local_types)
                if n is not None:
                    out.add(n)
    return out


# ---- call resolution --------------------------------------------------------


def local_ctor_types(fn: ast.FunctionDef) -> dict[str, str]:
    """Local variables assigned from constructor calls inside `fn`."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            leaf = _ctor_leaf(node.value)
            if leaf is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = leaf
    return out


def fn_local_types(fn: ast.FunctionDef, cls: ClassInfo | None,
                   prog: Program) -> dict[str, str]:
    """Constructor-typed locals plus attribute-chain snapshots
    (`task = self.task` types the local from the attribute)."""
    out = local_ctor_types(fn)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, (ast.Attribute, ast.Name)):
            d = dotted(node.value)
            if not d:
                continue
            parts = d.split(".")
            tname: str | None = None
            if len(parts) == 1:
                tname = NAME_TYPE_LEXICON.get(parts[0])
            else:
                owner = chain_class(parts[:-1], cls, prog, out)
                # attr_type falls back to the unique program-wide
                # owner even when the chain itself stayed untyped
                tname = prog.attr_type(owner, parts[-1])
            if tname is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id not in out:
                        out[t.id] = tname
    return out


def resolve_call(call: ast.Call, cls: ClassInfo | None,
                 module_rel: str, prog: Program,
                 local_types: dict[str, str]) -> ast.FunctionDef | None:
    """FunctionDef a dotted/bare call lands in, or None when the chain
    cannot be typed. Resolution is deliberately conservative: a broken
    hop gives up rather than guessing."""
    name = call_name(call)
    if not name:
        return None
    parts = name.split(".")
    method = parts[-1]
    chain = parts[:-1]
    if not chain:
        # bare call: module-level function in the same module
        return prog.module_funcs.get(module_rel, {}).get(method)
    cur = chain_class(chain, cls, prog, local_types)
    if cur is None:
        return None
    return cur.methods.get(method)


def _compute_acquires(prog: Program) -> None:
    """Fixpoint: transitive lock nodes each function acquires through
    `with` blocks plus every resolvable callee."""
    fns: list[tuple[ast.FunctionDef, ClassInfo | None, str]] = []
    for info in prog.classes:
        for m in info.methods.values():
            fns.append((m, info, info.rel))
    for rel, funcs in prog.module_funcs.items():
        for f in funcs.values():
            fns.append((f, None, rel))
    types_of: dict[int, dict[str, str]] = {
        id(fn): fn_local_types(fn, cls, prog) for fn, cls, _rel in fns}
    for fn, cls, rel in fns:
        prog.acquires[id(fn)] = direct_acquires(
            fn, cls, rel, prog, types_of[id(fn)])
    # resolve call targets once
    calls: dict[int, set[int]] = {}
    for fn, cls, rel in fns:
        local_types = types_of[id(fn)]
        targets: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                tgt = resolve_call(node, cls, rel, prog, local_types)
                if tgt is not None and id(tgt) != id(fn):
                    targets.add(id(tgt))
        calls[id(fn)] = targets
    changed = True
    while changed:
        changed = False
        for fn, _cls, _rel in fns:
            acc = prog.acquires[id(fn)]
            before = len(acc)
            for tid in calls[id(fn)]:
                acc |= prog.acquires.get(tid, set())
            if len(acc) != before:
                changed = True
