#!/usr/bin/env python
"""DEPRECATED shim: the metrics registry lint moved into the static
analysis suite as `tools/analyze/passes/registry.py` (ISSUE 4).

Equivalent invocation:

    python -m tools.analyze --only registry

This forwarder stays so older scripts/docs keep working; it warns and
delegates, exit code preserved.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def lint() -> int:
    print("metrics_lint: DEPRECATED — use "
          "`python -m tools.analyze --only registry`", file=sys.stderr)
    from tools.analyze import main

    return main(["--only", "registry"])


if __name__ == "__main__":
    sys.exit(lint())
