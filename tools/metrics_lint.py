#!/usr/bin/env python
"""Static registry check for the observability plane (ISSUE 3).

The reference gets its X-macro discipline for free: a metric exists iff
its `.inc` line does, so a typo'd call site fails to compile. Python
would defer that mistake to runtime (a KeyError on a cold code path,
or worse — a histogram nobody ever looks for). This lint restores the
compile-time property, in both directions:

  1. every `stream_stat_add` / `time_series_add` / `gauge_set` /
     `gauge_fn` / `observe` / `events.append(kind, ...)` call site
     whose metric argument is a string literal must name a metric
     present in the registries (hstream_tpu/stats);
  2. every registered metric / event kind must be referenced by at
     least one such call site somewhere in the tree — dead registry
     entries rot dashboards.

Dynamic call sites (metric passed as a variable) are skipped — those
hit the registries' own KeyError at runtime, which the holder raises
on every unregistered name.

Run from the repo root (CI runs it in the fast tier-1 job):

    python tools/metrics_lint.py
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hstream_tpu.stats import (  # noqa: E402
    GAUGES,
    HISTOGRAMS,
    PER_STREAM_COUNTERS,
    PER_STREAM_TIME_SERIES,
)
from hstream_tpu.stats.events import EVENT_KINDS  # noqa: E402

# call-method name -> (registry, registry display name)
COUNTER_CALLS = {"stream_stat_add", "stream_stat_get",
                 "stream_stat_getall"}
TS_CALLS = {"time_series_add", "time_series_get_rate",
            "time_series_peek_rate", "time_series_streams", "_ts"}
GAUGE_CALLS = {"gauge_set", "gauge_fn", "gauge_drop", "gauge_labels"}
HIST_CALLS = {"observe", "histogram_percentile", "_hist"}

REGISTRIES = {
    "counter": set(PER_STREAM_COUNTERS),
    "time_series": {name for name, _ in PER_STREAM_TIME_SERIES},
    "gauge": set(GAUGES),
    "histogram": {name for name, _b, _l in HISTOGRAMS},
    "event": set(EVENT_KINDS),
}

_CALL_KIND = {}
for n in COUNTER_CALLS:
    _CALL_KIND[n] = "counter"
for n in TS_CALLS:
    _CALL_KIND[n] = "time_series"
for n in GAUGE_CALLS:
    _CALL_KIND[n] = "gauge"
for n in HIST_CALLS:
    _CALL_KIND[n] = "histogram"

SCAN_ROOTS = ("hstream_tpu", "tools", "bench.py")


def _py_files() -> list[str]:
    out = []
    for root in SCAN_ROOTS:
        p = os.path.join(REPO, root)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, _dirs, files in os.walk(p):
            out.extend(os.path.join(dirpath, f) for f in files
                       if f.endswith(".py"))
    return out


def _method_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_events_append(call: ast.Call) -> bool:
    """`<something>.events.append(...)` / `journal.append(...)` /
    `self._journal(...)`: the event-kind call shapes used in-tree.
    Plain list .append(...) is excluded by requiring the kind literal
    to BE a registered-looking string (checked by the caller)."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "append":
        base = fn.value
        base_name = (base.attr if isinstance(base, ast.Attribute)
                     else base.id if isinstance(base, ast.Name) else "")
        return base_name in ("events", "journal", "_events", "_ring")
    if isinstance(fn, ast.Attribute) and fn.attr == "_journal":
        return True
    return False


# files whose literals do NOT count as "referenced" for the dead-entry
# check: the registries themselves, the exposition layer (HELP text
# names every metric), and tools (a metric only this lint mentions is
# still dead in production). tests/ are not scanned at all — they
# deliberately exercise the unregistered-name KeyError paths.
_NO_REFERENCE_CREDIT = (
    os.path.join("hstream_tpu", "stats", "__init__.py"),
    os.path.join("hstream_tpu", "stats", "events.py"),
    os.path.join("hstream_tpu", "stats", "prometheus.py"),
    "tools",
)


def lint() -> int:
    errors: list[str] = []
    referenced: dict[str, set[str]] = {k: set() for k in REGISTRIES}
    all_names = {n for names in REGISTRIES.values() for n in names}
    for path in _py_files():
        rel = os.path.relpath(path, REPO)
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=rel)
        except SyntaxError as e:
            errors.append(f"{rel}: syntax error: {e}")
            continue
        if not rel.startswith(_NO_REFERENCE_CREDIT):
            # dead-entry credit: ANY literal mention in production code
            # (call sites, routing dicts like handlers._RPC_HISTOGRAMS)
            for node in ast.walk(tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and node.value in all_names):
                    for kind, names in REGISTRIES.items():
                        if node.value in names:
                            referenced[kind].add(node.value)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # dynamic metric name: runtime KeyError covers it
            name = _method_name(node)
            kind = _CALL_KIND.get(name or "")
            if kind is not None:
                metric = first.value
                if metric in REGISTRIES[kind]:
                    referenced[kind].add(metric)
                else:
                    errors.append(
                        f"{rel}:{node.lineno}: {name}({metric!r}, ...) "
                        f"names an unregistered {kind} metric")
            elif _is_events_append(node):
                event = first.value
                if event in REGISTRIES["event"]:
                    referenced["event"].add(event)
                else:
                    errors.append(
                        f"{rel}:{node.lineno}: events.append({event!r}) "
                        f"names an unregistered event kind")
    # direction 2: registered but never referenced anywhere
    for kind, names in REGISTRIES.items():
        for name in sorted(names - referenced[kind]):
            errors.append(
                f"registry: {kind} metric {name!r} is registered but "
                f"never referenced by any call site")
    if errors:
        print(f"metrics_lint: {len(errors)} problem(s)")
        for e in errors:
            print(f"  {e}")
        return 1
    n = sum(len(v) for v in referenced.values())
    print(f"metrics_lint: OK ({n} registered metrics/kinds, "
          f"all call sites registered, no dead registry entries)")
    return 0


if __name__ == "__main__":
    sys.exit(lint())
