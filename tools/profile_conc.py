"""Sync-mode concurrency: do parallel device_put streams scale aggregate
wire bandwidth? (Forces sync mode first with a real fetch.)"""
from __future__ import annotations

import concurrent.futures as cf
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    # force sync mode
    # analyze: ok retrace-uncached-jit — one-shot profiling CLI
    np.asarray(jax.jit(lambda: jnp.zeros(1))())

    MB = 1024 * 1024
    buf8 = np.random.default_rng(0).integers(
        0, 2**31, size=2 * MB).astype(np.int32)  # 8MB

    def put_force(b):
        d = jax.device_put(b)
        # force: fetch a scalar derived on device so the transfer must land
        return float(np.asarray(jnp.sum(d[:2].astype(jnp.float32))))

    t0 = time.perf_counter()
    put_force(buf8)
    dt = time.perf_counter() - t0
    print(f"sync single 8MB put+force: {dt*1e3:.0f} ms -> "
          f"{buf8.nbytes/dt/1e6:.1f} MB/s")

    for n in (2, 4, 8):
        bufs = [buf8 + i for i in range(n)]
        pool = cf.ThreadPoolExecutor(n)
        t0 = time.perf_counter()
        list(pool.map(put_force, bufs))
        dt = time.perf_counter() - t0
        print(f"sync concurrent x{n} 8MB: {dt*1e3:.0f} ms -> "
              f"{n*buf8.nbytes/dt/1e6:.1f} MB/s aggregate")

    # downlink: fetch 8MB computed on device
    d = jax.device_put(buf8)
    dd = jnp.asarray(d) + 1  # computed -> not host-cached
    t0 = time.perf_counter()
    np.asarray(dd)
    dt = time.perf_counter() - t0
    print(f"downlink fetch 8MB computed: {dt*1e3:.0f} ms -> "
          f"{buf8.nbytes/dt/1e6:.1f} MB/s")

    # dispatch-only cost on resident data in sync mode
    st = jax.device_put(np.zeros((1024, 1024), np.float32))
    # analyze: ok retrace-uncached-jit — one-shot profiling CLI
    f = jax.jit(lambda s, x: s + jnp.sum(x.astype(jnp.float32)))
    float(np.asarray(jnp.sum(f(st, d))))  # compile
    t0 = time.perf_counter()
    for _ in range(10):
        st = f(st, d)
    dt = (time.perf_counter() - t0) / 10
    print(f"sync dispatch resident-arg jit: {dt*1e3:.1f} ms/call")
    t0 = time.perf_counter()
    np.asarray(st[0, 0])
    print(f"final force: {(time.perf_counter()-t0)*1e3:.0f} ms")


if __name__ == "__main__":
    main()
